"""Shared health-stream tailing for the monitor CLIs.

run_monitor.py, serve_monitor.py, sched_monitor.py and fleet_monitor.py
all consume the same append-only JSONL health streams
(lightgbm_tpu/utils/telemetry.py, schema ``lightgbm_tpu.health/v1``)
and used to duplicate three pieces of machinery, which live here once:

  * :class:`JsonlFolder` — incremental byte-offset folding of a JSONL
    stream, tolerating a torn trailing line (kept in a tail buffer
    until its newline arrives; the O_APPEND writer never tears a line,
    but a reader can race the write).
  * :func:`follow_stream` — the tail loop: byte-offset incremental
    reads (a multi-hour stream is not re-parsed every tick),
    truncation restart (a fresh run recreating the file), re-render on
    growth, and the 0/2/3 exit-code contract every monitor shares
    (0 = summary landed, 2 = file never appeared, 3 = timeout).
  * pace-relative staleness — :func:`median_record_gap` /
    :func:`stream_stale` / :func:`stream_age_s`: an unfinished stream
    whose file has no new line within ``STALL_GAP_FACTOR`` x its own
    median inter-record gap is flagged, catching a wedge that
    iteration-lag checks can't see (every rank stuck at the same
    iteration, or a single wedged tenant).

State classes need only subclass :class:`JsonlFolder`, implement
``on_record``, and keep a ``summary`` attribute (None until the
stream's terminal record lands) plus a ``recent`` sequence of
``(t, ...)`` tuples if they want staleness detection.
"""

import json
import os
import time

# a rank whose newest iteration trails the fleet median by at least
# this many iterations (with no summary record) is flagged as stalled
STALL_LAG_ITERS = 2
# an unfinished stream with no new line for longer than this factor
# times its own median inter-record gap is flagged as stale
STALL_GAP_FACTOR = 2.0
# a stream too young/sparse to have a meaningful gap history is never
# flagged; require this many timestamped records first
STALE_MIN_RECORDS = 4


class JsonlFolder:
    """Incremental JSONL folding base: feed() accepts raw bytes in any
    chunking, parses complete lines, and dispatches each record to the
    subclass's ``on_record``.  A torn trailing line waits in the tail
    buffer; unparseable lines are skipped (torn/corrupt)."""

    def __init__(self):
        self.records = 0
        self.summary = None
        self._tail = b""

    def feed(self, data: bytes) -> None:
        buf = self._tail + data
        lines = buf.split(b"\n")
        self._tail = lines.pop()        # b"" when data ended in newline
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            self.records += 1
            self.on_record(rec)

    def on_record(self, rec: dict) -> None:
        raise NotImplementedError


def read_stream(path, state):
    """Fold a whole stream file into ``state`` (one-shot mode).
    Returns the state; OSError propagates to the caller."""
    with open(path, "rb") as fh:
        state.feed(fh.read())
    return state


def median_record_gap(state):
    """Median inter-record gap in seconds over the stream's recent
    timestamped records; None when fewer than STALE_MIN_RECORDS carry
    a timestamp (too young to judge a pace from)."""
    ts = [entry[0] for entry in state.recent
          if isinstance(entry[0], (int, float))]
    if len(ts) < STALE_MIN_RECORDS:
        return None
    gaps = sorted(max(0.0, b - a) for a, b in zip(ts, ts[1:]))
    mid = len(gaps) // 2
    return (gaps[mid] if len(gaps) % 2
            else 0.5 * (gaps[mid - 1] + gaps[mid]))


def stream_stale(state, age_s):
    """``(age_s, gap)`` when an unfinished stream has appended nothing
    for longer than STALL_GAP_FACTOR x its own median inter-record gap
    (``age_s`` = seconds since the file last grew), else None.  Pure —
    the caller supplies the age so this works on mtimes, synthetic
    clocks in tests, and any stream kind alike."""
    if state.summary is not None or age_s is None:
        return None
    gap = median_record_gap(state)
    if gap is None or gap <= 0:
        return None
    if age_s > STALL_GAP_FACTOR * gap:
        return (float(age_s), float(gap))
    return None


def stream_age_s(path, now=None):
    """Seconds since the stream file last grew (mtime age); None when
    the file can't be statted."""
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    return max(0.0, (time.time() if now is None else now) - mtime)


def follow_stream(path, state_factory, render, interval, timeout, out,
                  name="monitor", timeout_msg=None):
    """Tail one stream until its terminal record lands.

    ``state_factory`` builds a fresh :class:`JsonlFolder` (also after a
    truncation — a fresh run recreating the file); ``render(state,
    path)`` returns the view re-printed on every growth.  Returns 0 on
    a completed stream, 2 when the file never appears before the
    deadline, 3 on timeout with the stream still unterminated."""
    state = state_factory()
    offset = 0
    deadline = time.monotonic() + timeout if timeout > 0 else None
    waited_for_file = False
    while True:
        if os.path.exists(path):
            size = os.path.getsize(path)
            if size < offset:            # truncated (fresh run): restart
                state, offset = state_factory(), 0
            if size > offset:
                with open(path, "rb") as fh:
                    fh.seek(offset)
                    data = fh.read()
                offset += len(data)
                state.feed(data)
                out.write(render(state, path) + "\n")
                out.flush()
        else:
            waited_for_file = True
        if state.summary is not None:
            return 0
        if deadline is not None and time.monotonic() >= deadline:
            if waited_for_file and state.records == 0:
                out.write(f"{name}: {path} never appeared\n")
                return 2
            out.write(timeout_msg or
                      f"{name}: timeout waiting for the summary "
                      "record\n")
            return 3
        time.sleep(interval)
