"""Micro: full-N histogram_all pass cost vs LIGHTGBM_TPU_ONEHOT_DTYPE.

One process per dtype (the env is read at kernel trace time and is not
part of the jit cache key).  HIGGS shape: F=28, B=64, N=10.5M padded.
Usage: LIGHTGBM_TPU_ONEHOT_DTYPE=i16 python tools/onehot_micro.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops.pallas_histogram import (histogram_all, pack_channels,
                                               pick_block_rows)

F, B, N = 28, 64, 10_500_000
rb = pick_block_rows(F, B)
n = ((N + rb - 1) // rb) * rb
rng = np.random.default_rng(0)
binsT = jnp.asarray(rng.integers(0, B, (F, n)), jnp.uint8)
w8 = pack_channels(jnp.asarray(rng.standard_normal(n), jnp.float32),
                   jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),
                   jnp.ones(n, jnp.float32))

t0 = time.time()
out = histogram_all(binsT, w8, B, rb)
jax.block_until_ready(out)
warm = time.time() - t0
reps = 20
t0 = time.time()
for _ in range(reps):
    out = histogram_all(binsT, w8, B, rb)
jax.block_until_ready(out)
per = (time.time() - t0) / reps
print(f"ONEHOT={os.environ.get('LIGHTGBM_TPU_ONEHOT_DTYPE', 'i32') or 'i32'}"
      f" full-N pass: {per * 1e3:.2f} ms (warmup {warm:.1f}s, rb={rb})"
      f" checksum={float(jnp.sum(out)):.3f}")
