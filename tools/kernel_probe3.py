"""Scaling + calibration probe: is the combined-onehot kernel measurement
real?  Time vs N must scale linearly; calibrate with a dense matmul."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")
F, B, CH, K = 28, 64, 8, 16


def timeit(fn, args, iters=30):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def make_exact(rb, chunk):
    def kernel(binsT_ref, w_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        for c in range(rb // chunk):
            b = binsT_ref[:, c * chunk:(c + 1) * chunk].astype(jnp.int32)
            iota = lax.broadcasted_iota(jnp.int32, (F, B, chunk), 1)
            onehot = (b[:, None, :] == iota).astype(
                jnp.bfloat16).reshape(F * B, chunk)
            acc_ref[:] += lax.dot_general(
                onehot, w_ref[:, c * chunk:(c + 1) * chunk],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            out_ref[:] = acc_ref[:]

    @jax.jit
    def run(binsT, w8):
        n = binsT.shape[1]
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((F * B, CH), jnp.float32),
            grid=(n // rb,),
            in_specs=[pl.BlockSpec((F, rb), lambda i: (0, i)),
                      pl.BlockSpec((CH, rb), lambda i: (0, i))],
            out_specs=pl.BlockSpec((F * B, CH), lambda i: (0, 0)),
            scratch_shapes=[pltpu.VMEM((F * B, CH), jnp.float32)],
        )(binsT, w8)
    return run


def make_wave(rb, chunk):
    def kernel(tgt_ref, binsT_ref, w_ref, lid_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        for c in range(rb // chunk):
            sl = slice(c * chunk, (c + 1) * chunk)
            b = binsT_ref[:, sl].astype(jnp.int32)
            iota = lax.broadcasted_iota(jnp.int32, (F, B, chunk), 1)
            onehot = (b[:, None, :] == iota).astype(
                jnp.bfloat16).reshape(F * B, chunk)
            l = lid_ref[:, sl]
            w = w_ref[:, sl]
            wk = jnp.concatenate(
                [w * (l == tgt_ref[k]).astype(jnp.bfloat16)
                 for k in range(K)], axis=0)
            acc_ref[:] += lax.dot_general(
                onehot, wk,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            out_ref[:] = acc_ref[:]

    @jax.jit
    def run(binsT, w8, lid, targets):
        n = binsT.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // rb,),
            in_specs=[pl.BlockSpec((F, rb), lambda i, s: (0, i)),
                      pl.BlockSpec((CH, rb), lambda i, s: (0, i)),
                      pl.BlockSpec((1, rb), lambda i, s: (0, i))],
            out_specs=pl.BlockSpec((F * B, K * CH), lambda i, s: (0, 0)),
            scratch_shapes=[pltpu.VMEM((F * B, K * CH), jnp.float32)],
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((F * B, K * CH), jnp.float32),
            grid_spec=grid_spec,
        )(targets, binsT, w8, lid.reshape(1, -1))
    return run


def main():
    rng = np.random.RandomState(0)
    from lightgbm_tpu.ops.pallas_histogram import pack_channels

    # calibration: dense bf16 matmul [4096,4096]x[4096,4096] = 68.7 GMAC
    a = jnp.asarray(rng.normal(size=(4096, 4096)).astype(np.float32),
                    dtype=jnp.bfloat16)
    bm = jnp.asarray(rng.normal(size=(4096, 4096)).astype(np.float32),
                     dtype=jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return jnp.dot(a, b, preferred_element_type=jnp.float32)

    t = timeit(mm, (a, bm))
    print(f"calib 4096^3 matmul: {t*1e3:.3f} ms -> {68.7e9/t/1e12:.1f} TMAC/s")

    rb = 16384
    for n_m in (1, 4, 16):
        n = n_m * 1_048_576
        bins = rng.randint(0, B, size=(F, n)).astype(np.uint8)
        binsT = jnp.asarray(bins)
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        w8 = pack_channels(g, g * g, jnp.ones(n, jnp.float32))
        lid = jnp.asarray(rng.randint(0, 255, size=n).astype(np.int32))
        fn = make_exact(rb, 512)
        t = timeit(fn, (binsT, w8), iters=10)
        print(f"exact [FB,8] n={n_m}M: {t*1e3:.3f} ms "
              f"({t/n*1e9:.3f} ns/row)")
        fnw = make_wave(rb, 512)
        targets = jnp.arange(K, dtype=jnp.int32)
        t = timeit(fnw, (binsT, w8, lid, targets), iters=10)
        print(f"wave [FB,{K*CH}] n={n_m}M: {t*1e3:.3f} ms "
              f"({t/n*1e9:.3f} ns/row)")
        if n_m == 1:
            out = np.asarray(fnw(binsT, w8, lid, targets))
            oh = out.reshape(F, B, K, CH)
            got = oh[..., 3, 0] + oh[..., 3, 1]
            sel = np.asarray(lid) == 3
            want = np.zeros((F, B))
            gn = np.asarray(g)
            for f in range(F):
                np.add.at(want[f], bins[f][sel], gn[sel])
            print("  wave leaf-3 grad max abs err:",
                  float(np.max(np.abs(got - want))))


main()
