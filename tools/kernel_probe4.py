"""Trustworthy timing: chain each iteration's input on the previous
output so the device cannot dedupe/overlap identical dispatches."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
F, B, CH, K = 28, 64, 8, 16

from tools.kernel_probe3 import make_exact, make_wave  # noqa: E402


def chain_time(step, state, iters=20):
    state = step(state)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(iters):
        state = step(state)
    jax.block_until_ready(state)
    return (time.perf_counter() - t0) / iters


def main():
    rng = np.random.RandomState(0)
    from lightgbm_tpu.ops.pallas_histogram import pack_channels

    a0 = jnp.asarray(rng.normal(size=(4096, 4096)).astype(np.float32),
                     dtype=jnp.bfloat16)
    bm = jnp.asarray(rng.normal(size=(4096, 4096)).astype(np.float32),
                     dtype=jnp.bfloat16)

    @jax.jit
    def mm_step(a):
        out = jnp.dot(a, bm, preferred_element_type=jnp.float32)
        return (out * (1.0 / 4096.0)).astype(jnp.bfloat16)

    t = chain_time(mm_step, a0)
    print(f"calib 4096^3 chained: {t*1e3:.3f} ms -> "
          f"{68.7e9/t/1e12:.1f} TMAC/s")

    rb = 16384
    exact = make_exact(rb, 512)
    wave = make_wave(rb, 512)
    for n_m in (1, 4):
        n = n_m * 1_048_576
        binsT = jnp.asarray(rng.randint(0, B, size=(F, n)).astype(np.uint8))
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        w8 = pack_channels(g, g * g, jnp.ones(n, jnp.float32))
        lid = jnp.asarray(rng.randint(0, 255, size=n).astype(np.int32))
        targets = jnp.arange(K, dtype=jnp.int32)

        @jax.jit
        def ex_step(w8):
            out = exact(binsT, w8)
            return w8 * (1.0 + 1e-12 * out[0, 0])

        t = chain_time(ex_step, w8)
        print(f"exact [FB,8] n={n_m}M chained: {t*1e3:.3f} ms "
              f"({t/n*1e9:.3f} ns/row)")

        @jax.jit
        def wv_step(w8):
            out = wave(binsT, w8, lid, targets)
            return w8 * (1.0 + 1e-12 * out[0, 0])

        t = chain_time(wv_step, w8)
        print(f"wave [FB,128] n={n_m}M chained: {t*1e3:.3f} ms "
              f"({t/n*1e9:.3f} ns/row)")


main()
