"""AOT compile-stage timing of grower components on the TPU backend.

Usage: python tools/compile_probe.py [variant ...]
variants: seg seg_nocompact fused kernel scan
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
N = 65536
F, B, L = 28, 64, 255
RB = 8192


def stage_time(name, make_lowered):
    t0 = time.perf_counter()
    lowered = make_lowered()
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    print(f"{name}: trace={t1-t0:.1f}s compile={t2-t1:.1f}s")
    return compiled


def main():
    variants = sys.argv[1:] or ["seg", "kernel", "scan", "fused"]
    rng = np.random.RandomState(0)
    binsT = jnp.asarray(rng.randint(0, B, size=(F, N)).astype(np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    member = jnp.ones(N, jnp.float32)
    key = jax.random.PRNGKey(0)
    from lightgbm_tpu.models.grower import GrowerParams
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    fmask = jnp.ones(F, jnp.float32)
    params = GrowerParams(num_leaves=L, hist_backend="pallas",
                          split=SplitParams(min_sum_hessian_in_leaf=100.0,
                                            has_cat=False))

    if "seg" in variants:
        from lightgbm_tpu.models.grower_seg import make_grow_tree_segment
        grow = make_grow_tree_segment(B, params, RB)
        stage_time("segment grower", lambda: grow.lower(
            binsT, g, g, member, fmeta, fmask, key))

    if "seg_nocompact" in variants:
        # compaction is now an unconditional lax.cond in the loop body, so
        # dropping it from the traced program requires stubbing compact()
        import lightgbm_tpu.models.grower_seg as gs
        saved_body = gs.make_grow_tree_segment
        import unittest.mock as _mock
        with _mock.patch.object(gs, "COMPACT_WASTE", 2.0**30):
            grow = gs.make_grow_tree_segment(B, params, RB)
            stage_time("segment grower (compaction threshold unreachable; "
                       "cond still traced)", lambda: grow.lower(
                binsT, g, g, member, fmeta, fmask, key))

    if "fused" in variants:
        from lightgbm_tpu.models.grower import make_grow_tree
        grow = make_grow_tree(B, params)
        stage_time("fused grower (pallas hist)", lambda: grow.lower(
            binsT, g, g, member, fmeta, fmask, key))

    if "kernel" in variants:
        from lightgbm_tpu.ops.pallas_histogram import (histogram_segment,
                                                       pack_channels)
        w8 = pack_channels(g, g, member)
        lid = jnp.zeros(N, jnp.int32)

        @jax.jit
        def seg(binsT, w8, lid):
            return histogram_segment(binsT, w8, lid, jnp.int32(0),
                                     jnp.int32(2), jnp.int32(0), B, RB)

        stage_time("segment kernel alone", lambda: seg.lower(binsT, w8, lid))

    if "scan" in variants:
        from lightgbm_tpu.ops.split import best_split

        @jax.jit
        def scan2(hist2):
            return jax.vmap(
                lambda h: best_split(h, jnp.float32(1.0), jnp.float32(2.0),
                                     jnp.float32(1e5), fmeta,
                                     params.split, fmask))(hist2)

        hist2 = jnp.ones((2, F, B, 3), jnp.float32)
        stage_time("vmapped pair best_split", lambda: scan2.lower(hist2))


main()
