"""Round-5 unattended on-chip measurement plan.

Runs from the MAIN tree the moment the backend answers (the round-4
backend outage spanned the whole previous round; tools/onchip.py is the
round-4 snapshot variant).  Ordered by value-per-chip-minute:

  0. device probe (exit 3 while the backend is down)
  1. kernel self-checks on REAL hardware: fused route+histogram and the
     one-hot scorer must lower and match bit-for-bit (auto-gates flip
     the fast paths on only if this passes — interpret-green is not
     lowering-green, ONCHIP_LOG round 4)
  2. bench.py FIRST (the scoreboard; internally A/Bs growers under the
     quality guard) — a short chip window must capture this above all;
     its children also record the COLD warmup_s in their JSON
  3. strict + frontier 10.5M probes at current defaults (first numbers
     ever for: epoch-loop restructure + windowed route + scorer +
     fused route)
  4. fused-route OFF A/B (attributes the new kernel's share)
  5. ONE warm rerun of the bench child: its warmup_s against step 2's
     cold number is the persistent-cache verdict (VERDICT r4 item 3
     needs warm <= 60 s)
  6. bench_suite.py (BASELINE configs 2-5, quality-gated)
  7. bf16/i16 one-hot + ROW_CHUNK=8192 exploration probes

Usage:
    python tools/onchip_r5.py          # run everything now
    python tools/onchip_r5.py --wait   # poll until the chip answers
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from onchip import PY, REPO, chip_up, log, run_step, wait_for_chip  # noqa: E402


def main():
    if "--wait" in sys.argv:
        if not wait_for_chip(max_wait_s=10 * 3600):
            log("r5 probe: backend never came up; giving up")
            sys.exit(3)
        log("r5 probe: backend UP — running plan r5")
    elif not chip_up():
        if "--if-up" in sys.argv:
            print("backend down; skipping (--if-up)")
            sys.exit(3)
        log("r5 probe: backend DOWN; proceeding anyway")
    else:
        log("r5 probe: backend UP — running plan r5")

    probe = os.path.join(REPO, "tools", "perf_probe.py")

    # 1. on-chip kernel self-checks (the auto-gates run these lazily;
    # running them eagerly here writes the verdict into the log)
    run_step("self-checks on chip", [PY, "-c", (
        "from lightgbm_tpu.ops.pallas_histogram import "
        "_fused_route_self_check;"
        "from lightgbm_tpu.ops.pallas_score import scorer_available;"
        "print('fused_route', _fused_route_self_check());"
        "print('scorer', scorer_available())")], 1200)

    # 2. THE SCOREBOARD FIRST: if the window is short, bench.py's
    # strict/frontier A/B is the artifact the round is judged on
    bench = os.path.join(REPO, "bench.py")
    run_step("bench (r5, first)", [PY, bench], 9000)

    # 3. headline probes at defaults (fused route auto-enables iff the
    # self-check above passed)
    run_step("strict r5 defaults 10.5M", [PY, probe, "10500000,255,1,3"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1"})
    run_step("frontier r5 defaults 10.5M", [PY, probe, "10500000,255,1,3"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier"})

    # 4. fused-route attribution A/B
    run_step("strict FUSED_ROUTE=0 10.5M", [PY, probe, "10500000,255,1,2"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_FUSED_ROUTE": "0"})
    run_step("frontier FUSED_ROUTE=0 10.5M",
             [PY, probe, "10500000,255,1,2"], 2400,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_IMPL": "frontier",
              "LIGHTGBM_TPU_FUSED_ROUTE": "0"})

    # 5. one WARM bench child: step 2's children logged the COLD
    # warmup_s before the cache had these shapes; this fresh process
    # re-reads them through the persistent cache — the pair is the
    # cold-vs-warm verdict
    run_step("warmup warm 10.5M",
             [PY, bench, "--child", "tpu", "10500000", "2", "2"], 2700)

    # 6. suite scoreboard
    run_step("bench_suite (r5)", [PY, os.path.join(REPO, "bench_suite.py")],
             10800)

    # 7. exploration probes
    run_step("frontier ONEHOT=bf16 10.5M", [PY, probe, "10500000,255,1,2"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier",
                    "LIGHTGBM_TPU_ONEHOT_DTYPE": "bf16"})
    run_step("frontier ONEHOT=i16 10.5M", [PY, probe, "10500000,255,1,2"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_IMPL": "frontier",
                    "LIGHTGBM_TPU_ONEHOT_DTYPE": "i16"})
    run_step("frontier ROW_CHUNK=8192 10.5M",
             [PY, probe, "10500000,255,1,2"], 2400,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_IMPL": "frontier",
              "LIGHTGBM_TPU_ROW_CHUNK": "8192"})
    run_step("strict WASTE=10 10.5M", [PY, probe, "10500000,255,1,2"],
             2400, {"LIGHTGBM_TPU_SEG_STATS": "1",
                    "LIGHTGBM_TPU_COMPACT_WASTE": "10.0"})

    # 8. if the window is still open, the round-4 snapshot plan
    # (.onchip_r5 worktree at the round-4 HEAD) attributes the round-4
    # fixes cleanly; it logs to its own ONCHIP_LOG.md
    snap = os.path.join(REPO, ".onchip_r5", "tools", "onchip.py")
    if os.path.exists(snap):
        run_step("plan 4c snapshot", [PY, snap, "--if-up"], 6 * 3600)

    log("plan r5 complete")


if __name__ == "__main__":
    main()
