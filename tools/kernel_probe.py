"""Microbenchmark: current pallas histogram kernel vs combined-onehot
prototype, plus compaction-sort cost.  Run on the real TPU.

Usage: python tools/kernel_probe.py [n_rows]
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
F = 28
B = 64
CH = 8


def timeit(fn, *args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    rng = np.random.RandomState(0)
    from lightgbm_tpu.ops.pallas_histogram import (
        histogram_all, pack_channels, pick_block_rows)

    rb_old = pick_block_rows(F, B)
    npad = -(-N // rb_old) * rb_old
    bins = rng.randint(0, B, size=(F, npad)).astype(np.uint8)
    binsT = jnp.asarray(bins)
    grad = jnp.asarray(rng.normal(size=npad).astype(np.float32))
    hess = jnp.asarray(rng.uniform(0.1, 1, size=npad).astype(np.float32))
    member = jnp.ones(npad, jnp.float32)
    w8 = pack_channels(grad, hess, member)

    t = timeit(lambda: histogram_all(binsT, w8, B, rb_old))
    print(f"old histogram_all rb={rb_old}: {t*1e3:.2f} ms "
          f"({t/npad*1e9:.2f} ns/row)")

    # ---- prototype: combined (f, bin) one-hot, single matmul per chunk
    def make_proto(rb, chunk):
        def kernel(binsT_ref, w_ref, out_ref, acc_ref):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            n_chunks = rb // chunk
            for c in range(n_chunks):
                b = binsT_ref[:, c * chunk:(c + 1) * chunk].astype(jnp.int32)
                iota = lax.broadcasted_iota(jnp.int32, (F, B, chunk), 1)
                onehot = (b[:, None, :] == iota).astype(
                    jnp.bfloat16).reshape(F * B, chunk)
                w = w_ref[:, c * chunk:(c + 1) * chunk]
                acc_ref[:] += lax.dot_general(
                    onehot, w, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)

            @pl.when(i == pl.num_programs(0) - 1)
            def _():
                out_ref[:] = acc_ref[:]

        @jax.jit
        def run(binsT, w8):
            n = binsT.shape[1]
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((F * B, CH), jnp.float32),
                grid=(n // rb,),
                in_specs=[
                    pl.BlockSpec((F, rb), lambda i: (0, i)),
                    pl.BlockSpec((CH, rb), lambda i: (0, i)),
                ],
                out_specs=pl.BlockSpec((F * B, CH), lambda i: (0, 0)),
                scratch_shapes=[pltpu.VMEM((F * B, CH), jnp.float32)],
            )(binsT, w8)
        return run

    for rb, chunk in [(8192, 512), (16384, 512), (32768, 512),
                      (32768, 1024), (32768, 2048), (65536, 2048)]:
        if npad % rb:
            continue
        try:
            fn = make_proto(rb, chunk)
            t = timeit(lambda: fn(binsT, w8))
            print(f"proto combined rb={rb} chunk={chunk}: {t*1e3:.2f} ms "
                  f"({t/npad*1e9:.2f} ns/row)")
        except Exception as e:
            print(f"proto rb={rb} chunk={chunk} FAILED: "
                  f"{type(e).__name__}: {str(e)[:200]}")

    # numerical check old vs proto
    fn = make_proto(8192, 512)
    ref = histogram_all(binsT, w8, B, rb_old)  # [F, 8, B]
    got = fn(binsT, w8).reshape(F, B, CH).transpose(0, 2, 1)
    print("max abs diff old-vs-proto:", float(jnp.max(jnp.abs(ref - got))))

    # ---- compaction sort cost
    lid = jnp.asarray(rng.randint(0, 255, size=npad).astype(np.int32))
    payload = [jnp.asarray(rng.randint(-2**31, 2**31 - 1, size=npad,
                                       dtype=np.int64).astype(np.int32))
               for _ in range(12)]

    @jax.jit
    def do_sort(lid, *pay):
        return lax.sort((lid,) + pay, num_keys=1, is_stable=True)

    t = timeit(lambda: do_sort(lid, *payload), iters=3)
    print(f"stable sort 12-word payload: {t*1e3:.1f} ms")

    # ---- O(N) per-split routing cost (fcol gather + where)
    @jax.jit
    def route(binsT, lid, f):
        fcol = lax.dynamic_slice_in_dim(binsT, f, 1, axis=0)[0]
        go_left = fcol <= 31
        return jnp.where((lid == 3) & ~go_left, 77, lid)

    t = timeit(lambda: route(binsT, lid, jnp.int32(5)), iters=20)
    print(f"full-N route step: {t*1e3:.2f} ms")


main()
