"""Chained, dtype-stable probe at 8M rows: sorts, gathers, kernels.

Chaining rule: every step consumes the previous step's output arrays
unchanged in dtype/shape, so no recompiles and no dispatch dedupe.
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, ".")
F, B, CH, K = 28, 64, 8, 16
N = 8 * 1024 * 1024
RB = 16384


def chain_time(step, state, iters=8, label=""):
    state = step(*state)          # compile + warm
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(iters):
        state = step(*state)
    jax.block_until_ready(state)
    dt = (time.perf_counter() - t0) / iters
    print(f"{label}: {dt*1e3:.2f} ms")
    return dt


def main():
    rng = np.random.RandomState(0)
    from lightgbm_tpu.ops.pallas_histogram import pack_channels
    from tools.kernel_probe3 import make_exact, make_wave

    lid = jnp.asarray(rng.randint(0, 255, size=N).astype(np.int32))
    words12 = [jnp.asarray(rng.randint(-2**31, 2**31 - 1, size=N,
                                       dtype=np.int64).astype(np.int32))
               for _ in range(12)]
    order = jnp.arange(N, dtype=jnp.int32)

    # (a) 12-word stable sort
    @jax.jit
    def s12(lid, *pay):
        out = lax.sort((lid,) + pay, num_keys=1, is_stable=True)
        # rotate so next call's key differs
        return (out[1],) + out[2:] + (out[0],)

    chain_time(s12, (lid, *words12), iters=5, label="sort 12-word")

    # (b) 2-word stable sort (argsort)
    @jax.jit
    def s2(lid, order):
        k, v = lax.sort((lid, order), num_keys=1, is_stable=True)
        return v, k

    chain_time(s2, (lid, order), iters=8, label="sort 2-word")

    # (c) row gather [N, 12] i32 by permutation
    rows = jnp.stack(words12, axis=1)          # [N, 12]
    perm = jnp.asarray(rng.permutation(N).astype(np.int32))

    @jax.jit
    def rowgat(rows, perm):
        out = jnp.take(rows, perm, axis=0)
        return out, out[:, 0].astype(jnp.int32) % N

    chain_time(rowgat, (rows, perm), iters=8, label="row gather [N,12] i32")

    # (d) transpose [F,N] u8 <-> [N,F]
    binsT = jnp.asarray(rng.randint(0, B, size=(F, N)).astype(np.uint8))

    @jax.jit
    def tr(binsT):
        r = binsT.T                            # [N, F]
        return (r.T,)

    chain_time(lambda b: tr(b), (binsT,), iters=5,
               label="transpose u8 [F,N]->[N,F]->[F,N] (x2)")

    # (e) lane gather [F, N] u8 by permutation
    @jax.jit
    def lanegat(binsT, perm):
        out = jnp.take(binsT, perm, axis=1)
        return out, out[0].astype(jnp.int32) % N

    chain_time(lanegat, (binsT, perm), iters=3, label="lane gather [F,N] u8")

    # (f) kernels, dtype-stable chaining (bf16 stays bf16)
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    w8 = pack_channels(g, g * g, jnp.ones(N, jnp.float32))
    exact = make_exact(RB, 512)
    wave = make_wave(RB, 512)
    targets = jnp.arange(K, dtype=jnp.int32)

    @jax.jit
    def ex_step(w8):
        out = exact(binsT, w8)
        nudge = (1.0 + 1e-12 * out[0, 0]).astype(jnp.bfloat16)
        return (w8 * nudge,)

    chain_time(lambda w: ex_step(w), (w8,), iters=8,
               label="exact [FB,8] kernel+nudge")

    @jax.jit
    def wv_step(w8):
        out = wave(binsT, w8, lid, targets)
        nudge = (1.0 + 1e-12 * out[0, 0]).astype(jnp.bfloat16)
        return (w8 * nudge,)

    chain_time(lambda w: wv_step(w), (w8,), iters=8,
               label="wave [FB,128] kernel+nudge")

    # (g) the nudge alone, to subtract its cost
    @jax.jit
    def nudge_only(w8):
        return (w8 * jnp.bfloat16(1.0),)

    chain_time(lambda w: nudge_only(w), (w8,), iters=8, label="nudge alone")

    # (h) old per-feature kernel for comparison
    from lightgbm_tpu.ops.pallas_histogram import histogram_all

    @jax.jit
    def old_step(w8):
        out = histogram_all(binsT, w8, B, 8192)
        nudge = (1.0 + 1e-12 * out[0, 0, 0]).astype(jnp.bfloat16)
        return (w8 * nudge,)

    chain_time(lambda w: old_step(w), (w8,), iters=8,
               label="OLD per-feature kernel+nudge")


main()
