"""Launch an N-process CPU-backend ``jax.distributed`` run on localhost.

The 2-process CPU harness is how the multi-host robustness layer
(`parallel/distributed.py`) is *tested* rather than asserted: real
``jax.distributed.initialize`` against a real coordination service,
real KV-store collectives and barriers, real process death — just
without a TPU pod.  Used by the slow-marked tests in
tests/test_distributed.py and runnable by hand:

    python tools/launch_multihost.py --hosts 2 -- \
        python -m lightgbm_tpu train.conf output_model=/tmp/m{rank}.txt

``{rank}`` in any argv token expands to the process's host rank.  Each
child gets JAX_PLATFORMS=cpu (axon sitecustomize neutralized), an even
share of virtual CPU devices, and the LIGHTGBM_TPU_COORDINATOR_ADDRESS/
_NUM_HOSTS/_HOST_RANK env vars that drive
``distributed.maybe_initialize``.

The module API (`launch`) additionally takes per-rank argv lists — the
preemption tests arm the ``dist/preempt`` fault site on ONE rank only —
and per-rank extra env, and can deliver a late SIGKILL to a chosen rank
to simulate a host dying mid-run.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_port() -> int:
    """An OS-assigned free TCP port (racy by nature, fine for tests)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def rank_env(rank: int, num_hosts: int, port: int,
             devices_per_host: int = 2,
             extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Child-process environment for one host rank."""
    from lightgbm_tpu.utils import cpu_subprocess_env
    env = cpu_subprocess_env(n_virtual_devices=devices_per_host)
    # children may run from any cwd (tests chdir into tmp dirs)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env["LIGHTGBM_TPU_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    env["LIGHTGBM_TPU_NUM_HOSTS"] = str(num_hosts)
    env["LIGHTGBM_TPU_HOST_RANK"] = str(rank)
    if extra:
        env.update(extra)
    return env


class MultihostRun:
    """Handle over the fleet: per-rank Popen objects + helpers."""

    def __init__(self, procs: List[subprocess.Popen], port: int):
        self.procs = procs
        self.port = port

    def kill_rank(self, rank: int) -> None:
        """SIGKILL one host — the uncoordinated death the barrier
        timeouts exist for."""
        self.procs[rank].kill()

    def wait(self, timeout_s: float = 300.0) -> List[int]:
        """Wait for every rank; returns return codes (rank order)."""
        deadline = time.monotonic() + timeout_s
        codes = []
        for p in self.procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                codes.append(p.wait(timeout=left))
            except subprocess.TimeoutExpired:
                p.kill()
                codes.append(p.wait())
        return codes

    def terminate_all(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.kill()


def launch(argvs: Sequence[Sequence[str]],
           devices_per_host: int = 2,
           port: Optional[int] = None,
           extra_env: Optional[Sequence[Optional[Dict[str, str]]]] = None,
           cwds: Optional[Sequence[Optional[str]]] = None,
           stdouts: Optional[Sequence] = None) -> MultihostRun:
    """Spawn ``len(argvs)`` host processes, one per rank.

    ``argvs[r]`` is rank r's full argv (``{rank}`` tokens substituted);
    ``extra_env[r]`` merges rank-specific env on top (e.g. a
    LIGHTGBM_TPU_FAULTS spec armed on one rank only); ``cwds[r]`` is
    rank r's working directory (tests run each rank in its own dir with
    identical relative-path argv, keeping saved models byte-comparable
    across runs); ``stdouts[r]`` is a per-rank log file object (stderr
    is folded in).
    """
    num_hosts = len(argvs)
    port = port or free_port()
    procs = []
    for r, argv in enumerate(argvs):
        env = rank_env(r, num_hosts, port,
                       devices_per_host=devices_per_host,
                       extra=(extra_env[r] if extra_env else None))
        argv = [str(a).replace("{rank}", str(r)) for a in argv]
        out = stdouts[r] if stdouts else None
        procs.append(subprocess.Popen(
            argv, env=env, cwd=(cwds[r] if cwds else None),
            stdout=out, stderr=(subprocess.STDOUT if out else None)))
    return MultihostRun(procs, port)


def main() -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="run COMMAND once per host rank under a localhost "
                    "jax.distributed world ({rank} expands in args)")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--devices-per-host", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run (prefix with -- )")
    args = ap.parse_args()
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given")
    run = launch([cmd] * args.hosts,
                 devices_per_host=args.devices_per_host)
    codes = run.wait(timeout_s=args.timeout)
    for r, c in enumerate(codes):
        print(f"rank {r}: exit {c}")
    return max(abs(c) for c in codes)


if __name__ == "__main__":
    sys.exit(main())
