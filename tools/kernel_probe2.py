"""Careful re-measurement: serialized timing via dependency chains.

Measures:
  1. combined-onehot kernel [FB, 8] out (exact-mode shape)
  2. wave kernel [FB, K*8] out with leaf masking (wave-mode shape)
  3. sort with 2-word payload + row gather (compaction alternative)
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_048_576
F = 28
B = 64
CH = 8
K = 16


def timeit_chain(fn, x, extra, iters=30):
    """fn(x, *extra) -> y with y feeding back via a scalar nudge, forcing
    serialization."""
    out = fn(x, *extra)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x, *extra)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    rng = np.random.RandomState(0)
    rb = 16384
    npad = -(-N // rb) * rb
    bins = rng.randint(0, B, size=(F, npad)).astype(np.uint8)
    binsT = jnp.asarray(bins)
    g = jnp.asarray(rng.normal(size=npad).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, size=npad).astype(np.float32))
    from lightgbm_tpu.ops.pallas_histogram import pack_channels
    w8 = pack_channels(g, h, jnp.ones(npad, jnp.float32))
    lid = jnp.asarray(rng.randint(0, 255, size=npad).astype(np.int32))

    # ---------- 1. combined one-hot [FB, CH] ----------
    def make_exact(rb, chunk):
        def kernel(binsT_ref, w_ref, out_ref, acc_ref):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            for c in range(rb // chunk):
                b = binsT_ref[:, c * chunk:(c + 1) * chunk].astype(jnp.int32)
                iota = lax.broadcasted_iota(jnp.int32, (F, B, chunk), 1)
                onehot = (b[:, None, :] == iota).astype(
                    jnp.bfloat16).reshape(F * B, chunk)
                acc_ref[:] += lax.dot_general(
                    onehot, w_ref[:, c * chunk:(c + 1) * chunk],
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)

            @pl.when(i == pl.num_programs(0) - 1)
            def _():
                out_ref[:] = acc_ref[:]

        @jax.jit
        def run(binsT, w8):
            n = binsT.shape[1]
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((F * B, CH), jnp.float32),
                grid=(n // rb,),
                in_specs=[pl.BlockSpec((F, rb), lambda i: (0, i)),
                          pl.BlockSpec((CH, rb), lambda i: (0, i))],
                out_specs=pl.BlockSpec((F * B, CH), lambda i: (0, 0)),
                scratch_shapes=[pltpu.VMEM((F * B, CH), jnp.float32)],
            )(binsT, w8)
        return run

    fn = make_exact(rb, 512)
    t = timeit_chain(fn, binsT, (w8,))
    print(f"exact [FB,8] rb={rb}: {t*1e3:.3f} ms/pass "
          f"({14.3e9*(npad/1e6)/t/1e12:.1f} eff TMAC/s)")

    # ---------- 2. wave kernel [FB, K*8] with leaf masking ----------
    def make_wave(rb, chunk):
        def kernel(tgt_ref, binsT_ref, w_ref, lid_ref, out_ref, acc_ref):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            for c in range(rb // chunk):
                sl = slice(c * chunk, (c + 1) * chunk)
                b = binsT_ref[:, sl].astype(jnp.int32)
                iota = lax.broadcasted_iota(jnp.int32, (F, B, chunk), 1)
                onehot = (b[:, None, :] == iota).astype(
                    jnp.bfloat16).reshape(F * B, chunk)
                l = lid_ref[:, sl]                      # [1, chunk]
                w = w_ref[:, sl]                        # [CH, chunk]
                # [K*CH, chunk]: channel block k = w8 masked to leaf tgt[k]
                tk = tgt_ref[:]                          # [K] scalars
                masks = [(l == tk[k]).astype(jnp.bfloat16) for k in range(K)]
                wk = jnp.concatenate([w * m for m in masks], axis=0)
                acc_ref[:] += lax.dot_general(
                    onehot, wk,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)

            @pl.when(i == pl.num_programs(0) - 1)
            def _():
                out_ref[:] = acc_ref[:]

        @jax.jit
        def run(binsT, w8, lid, targets):
            n = binsT.shape[1]
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n // rb,),
                in_specs=[pl.BlockSpec((F, rb), lambda i, s: (0, i)),
                          pl.BlockSpec((CH, rb), lambda i, s: (0, i)),
                          pl.BlockSpec((1, rb), lambda i, s: (0, i))],
                out_specs=pl.BlockSpec((F * B, K * CH), lambda i, s: (0, 0)),
                scratch_shapes=[pltpu.VMEM((F * B, K * CH), jnp.float32)],
            )
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((F * B, K * CH), jnp.float32),
                grid_spec=grid_spec,
            )(targets, binsT, w8, lid.reshape(1, -1))
        return run

    targets = jnp.arange(K, dtype=jnp.int32)
    for chunk in (512, 1024):
        fnw = make_wave(rb, chunk)
        t = timeit_chain(fnw, binsT, (w8, lid, targets))
        print(f"wave [FB,{K*CH}] rb={rb} chunk={chunk}: {t*1e3:.3f} ms/pass "
              f"({229e9*(npad/1e6)/t/1e12:.1f} eff TMAC/s)")

    # correctness of wave kernel vs numpy for one leaf
    out = np.asarray(fnw(binsT, w8, lid, targets))
    got = out.reshape(F, B, K, CH)[..., 3, 0] + out.reshape(F, B, K, CH)[..., 3, 1]
    sel = np.asarray(lid) == 3
    want = np.zeros((F, B))
    gn = np.asarray(g)
    for f in range(F):
        np.add.at(want[f], bins[f][sel], gn[sel])
    print("wave leaf-3 grad max rel err:",
          float(np.max(np.abs(got - want)) / (np.abs(want).max() + 1e-9)))

    # ---------- 3. light sort + gather ----------
    @jax.jit
    def sort2(lid, order):
        return lax.sort((lid, order), num_keys=1, is_stable=True)

    order = jnp.arange(npad, dtype=jnp.int32)
    t = timeit_chain(lambda l, o: sort2(l, o), lid, (order,), iters=10)
    print(f"sort 2-word: {t*1e3:.2f} ms")

    rows = jnp.asarray(
        rng.randint(-2**31, 2**31 - 1, size=(npad, 7), dtype=np.int64)
        .astype(np.int32))
    perm = jnp.asarray(rng.permutation(npad).astype(np.int32))

    @jax.jit
    def gat(rows, perm):
        return jnp.take(rows, perm, axis=0)

    t = timeit_chain(gat, rows, (perm,), iters=10)
    print(f"row gather [N,7] i32: {t*1e3:.2f} ms")

    @jax.jit
    def gat_lane(binsT, perm):
        return jnp.take(binsT, perm, axis=1)

    t = timeit_chain(gat_lane, binsT, (perm,), iters=3)
    print(f"lane gather [F,N] u8: {t*1e3:.2f} ms")


main()
