"""Generate docs/PARAMETERS.md from the config schema.

The reference generates docs/Parameters.rst + the alias table from
config.h doc comments via helpers/parameter_generator.py (SURVEY §5);
here config.py's ``_PARAMS`` registry is the single source of truth and
this script derives the user-facing parameter reference from it.

Usage: python tools/gen_params_doc.py [--check]
  --check  exit 1 if docs/PARAMETERS.md is stale (for tests)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def generate() -> str:
    from lightgbm_tpu.config import _PARAMS

    lines = [
        "# Parameters",
        "",
        "Generated from `lightgbm_tpu/config.py` (`_PARAMS`) by",
        "`tools/gen_params_doc.py` — do not edit by hand.  The registry is",
        "the single source of truth for names, aliases, types and",
        "defaults (the reference generates its docs/Parameters.rst the",
        "same way from config.h via helpers/parameter_generator.py).",
        "",
        "Aliases resolve wherever parameters are accepted: Python dicts,",
        "`key=value` CLI arguments, and conf files.",
        "",
        "| Parameter | Default | Type | Aliases |",
        "|---|---|---|---|",
    ]
    for name, spec in _PARAMS.items():
        default = spec.default
        if default == "":
            default = '`""`'
        elif isinstance(default, list):
            default = "`[]`" if not default else f"`{default}`"
        else:
            default = f"`{default}`"
        ptype = spec.ptype.__name__
        aliases = ", ".join(spec.aliases) if spec.aliases else "—"
        lines.append(f"| `{name}` | {default} | {ptype} | {aliases} |")
    lines += [
        "",
        f"Total: {len(_PARAMS)} parameters, "
        f"{sum(len(s.aliases) for s in _PARAMS.values())} aliases.",
        "",
        "## TPU-specific parameters",
        "",
        "These have no reference equivalent (the `gpu_*` parameters are",
        "accepted for compatibility but ignored):",
        "",
        "- `tpu_histogram_backend` — `auto | onehot | pallas`: histogram",
        "  implementation; `pallas` is the TPU kernel path, `onehot` the",
        "  portable XLA fallback.",
        "- `tpu_tree_impl` — `auto | fused | segment | frontier`: tree",
        "  grower.  `segment` keeps per-split cost O(leaf) via epoch",
        "  compaction; `frontier` batches K splits per round into one",
        "  128-channel MXU kernel pass (batched best-first; K=1 is exactly",
        "  strict best-first).",
        "- `tpu_frontier_width` — leaves per frontier round (0 = auto:",
        "  min(16, ceil(num_leaves/16))).",
        "- `tpu_frontier_gain_ratio` — within a frontier round, only",
        "  batch leaves whose cached gain is at least this fraction of",
        "  the round's best gain (range [0, 1]; 0.0 = batch every",
        "  positive-gain leaf).  Lets rounds adapt between strict",
        "  best-first (one dominant leaf) and fully batched growth.",
        "- `tpu_row_chunk` — histogram kernel row-block size (0 = auto).",
        "- `tpu_boost_chunk` — boosting iterations dispatched as ONE",
        "  device program (`lax.scan` over the fused step) with all tree",
        "  fetches batched at the chunk boundary.  `0` = auto (chunk on",
        "  TPU when the run is chunk-eligible, per-iteration elsewhere);",
        "  `1` disables chunking.  Auto-clamps to 1 whenever an iteration",
        "  needs host interaction (bagging re-draws, feature sampling,",
        "  DART/RF tree mutation, GOSS, CEGB state, custom gradients,",
        "  per-iteration callbacks) and never changes a run's eval",
        "  cadence; an explicit value > 1 opts eval and early stopping",
        "  into chunk-boundary granularity.",
        "- `tpu_double_precision` — accumulate histograms in",
        "  f64-equivalent precision.",
        "- `telemetry_level` — training telemetry (see",
        "  docs/OBSERVABILITY.md): `0` off, `1` (default) counters +",
        "  gauges + per-iteration timeline, `2` adds spans for Chrome",
        "  trace export.  The `LIGHTGBM_TPU_TELEMETRY` env var overrides;",
        "  a set `LIGHTGBM_TPU_TRACE_JSON=<path>` forces level >= 2 and",
        "  writes the trace there.",
        "- `metrics_out` — CLI training only: write the versioned",
        "  telemetry JSON blob (schema `lightgbm_tpu.metrics/v6`) to this",
        "  path after training.  Written even when training crashes, so",
        "  the blob's `faults` section survives for post-mortems.",
        "- `device_timing` — measured per-dispatch device timing",
        "  (default `false`): each instrumented jit seam's dispatch is",
        "  synced wall-to-ready and accumulated into the metrics blob's",
        "  `timing` section (per-label count/mean/p50/p99 + dispatch",
        "  gaps, and measured-vs-estimated utilization).  Values and",
        "  models are unchanged, but the sync serializes the async",
        "  pipeline — an opt-in measurement mode, never a default.  The",
        "  `LIGHTGBM_TPU_DEVICE_TIMING` env var overrides.  Runtime-only:",
        "  never serialized into the model.",
        "- `profile_window` — windowed programmatic jax-profiler capture",
        "  (`START:END`, half-open boosting-iteration span): the trace",
        "  opens/closes exactly at those iterations, chunk dispatches",
        "  are split at the boundaries, and the artifact path + actual",
        "  window are recorded in the metrics blob's `timing.profile`.",
        "  The `LIGHTGBM_TPU_PROFILE_WINDOW` env var overrides; the",
        "  artifact dir is `LIGHTGBM_TPU_PROFILE_DIR` or",
        "  `lightgbm_tpu.profile`.  Runtime-only: never serialized into",
        "  the model.",
        "- `health_out` — stream the run-health JSONL there during",
        "  training (schema `lightgbm_tpu.health/v1`): per-iteration",
        "  gradient/hessian stats, tree shape, chunk size, HBM, eval/",
        "  snapshot/fault events.  Works from every entry point (CLI,",
        "  `engine.train`, sklearn); the `LIGHTGBM_TPU_HEALTH_JSONL` env",
        "  var overrides.  On `resume=true` the existing stream is",
        "  compacted past the snapshot iteration and appended to, giving",
        "  one contiguous stream.  Tail it with `tools/run_monitor.py`",
        "  (see docs/OBSERVABILITY.md).",
        "- `check_nonfinite` — finiteness guardrail on the boosted score",
        "  buffer (default `true`): a NaN/Inf iteration (diverged",
        "  objective, bad learning rate) is rolled back to the last good",
        "  iteration and reported with an actionable error instead of",
        "  silently corrupting every later tree.  Costs one device->host",
        "  scalar sync per iteration/chunk boundary; set `false` to trade",
        "  the guardrail for that sync (see docs/ROBUSTNESS.md).",
        "- `resume` — CLI training only: discover the newest",
        "  `<output_model>.snapshot_iter_N` (+ its `.state.npz` exact-state",
        "  sidecar) and continue training from iteration N, bit-exactly —",
        "  the final model is byte-identical to an uninterrupted run.",
        "  Runtime-only: never serialized into the model's `parameters:`",
        "  section.",
        "- `snapshot_keep` — retain only the newest K snapshots",
        "  (model + sidecar); `0` (default) keeps all, matching the",
        "  reference `save_period` behavior.",
        "- `data_in_hbm` — where the binned feature matrix lives during",
        "  training (default `auto`): `auto` runs a proactive admission",
        "  check before the first dispatch (estimated working set vs the",
        "  device's reported HBM capacity) and starts out-of-core when",
        "  the matrix won't fit; `resident` pins it in HBM (the",
        "  memory-pressure ladder then ends at chunk size 1); `spill`",
        "  forces the host-spill tier — the matrix stays in host memory",
        "  (optionally mmap-backed via `LIGHTGBM_TPU_SPILL_MMAP`) and is",
        "  streamed into HBM as fixed-order row-blocks per dispatch",
        "  window (`LIGHTGBM_TPU_SPILL_BLOCK_MB`, default 64).  Models",
        "  are bit-identical across tiers.  Runtime-only: never",
        "  serialized into the model.  See docs/ROBUSTNESS.md (the",
        "  recovery ladder) and docs/OBSERVABILITY.md (`data_tier`).",
        "- `fault_injection` — deterministic fault-injection spec",
        "  (`SITE[@START][xCOUNT]`, comma-separated) for robustness",
        "  testing; the `LIGHTGBM_TPU_FAULTS` env var overrides per-site.",
        "  Runtime-only: never serialized into the model.  See",
        "  docs/ROBUSTNESS.md for the grammar and the site list.",
        "- `coordinator_address` / `num_hosts` / `host_rank` — explicit",
        "  multi-host launch spec for `jax.distributed.initialize`",
        "  (`host:port`, world size, this process's rank; `host_rank=-1`",
        "  auto-detects from SLURM/OpenMPI launcher variables).  The",
        "  `LIGHTGBM_TPU_COORDINATOR_ADDRESS` / `_NUM_HOSTS` /",
        "  `_HOST_RANK` env vars (what `tools/launch_multihost.py` sets)",
        "  take priority; a partial spec is a loud error.  An externally",
        "  initialized world is adopted, never re-initialized.",
        "  Runtime-only: never serialized into the model.  See",
        "  docs/ROBUSTNESS.md (multi-host recovery).",
        "- `collective_retries` — attempts beyond the first for every",
        "  cross-host collective seam (object allgather, the",
        "  pre-dispatch reduce-scatter probe, distributed init); default",
        "  `1` preserves the historical retry-once.  `0` disables",
        "  retries.  Each retry is a `collective_retry` fault event.",
        "  Runtime-only: never serialized into the model.",
        "- `collective_timeout_s` — per-attempt budget (seconds, default",
        "  `120`) for KV-store collectives and the cross-host barriers at",
        "  snapshot/resume/preempt boundaries.  An expired barrier raises",
        "  an error naming the missing rank(s) instead of hanging the",
        "  fleet.  Runtime-only: never serialized into the model.",
        "- `predict_device` — where `Booster.predict` routes the tree",
        "  walk (default `auto`): `auto` uses the compiled device router",
        "  only when an accelerator backend is attached (on CPU the jit",
        "  dispatch overhead swamps the host walk), `on` forces it",
        "  everywhere (parity testing), `off` keeps the host float walk.",
        "  Both paths are bit-identical: the device returns per-tree leaf",
        "  INDICES and the float64 leaf-value accumulation stays on the",
        "  host in reference order.  Runtime-only: never serialized into",
        "  the model.  See docs/SERVING.md.",
        "- `serve_max_batch` — prediction-service micro-batch row cap",
        "  (default `256`): requests drained from the serve queue are",
        "  coalesced up to this many rows per compiled dispatch, and it",
        "  bounds the bucket ladder (8, 16, ... up to the cap) the",
        "  executable cache compiles.  See docs/SERVING.md.",
        "- `serve_max_delay_ms` — how long (default `2.0`) the serve",
        "  queue holds an under-full batch open hoping for co-batchable",
        "  requests; `0` dispatches immediately.  The knob IS the",
        "  latency-vs-throughput tradeoff — BENCH_SERVE.json measures",
        "  both settings.",
        "- `serve_queue_timeout_s` — end-to-end budget (default `30`)",
        "  for a blocking `ServeSession.predict` call; expiry raises a",
        "  named give-up instead of hanging the caller.",
        "- `sched` — CLI entry into the multi-tenant training scheduler:",
        "  path to a job-spec file (`job = NAME` sections over shared",
        "  defaults, see docs/SCHEDULING.md).  `python -m lightgbm_tpu",
        "  sched=jobs.spec` time-slices every job on one device set;",
        "  each finished job is byte-identical to a standalone run.",
        "  Runtime-only: never serialized into the model.",
        "- `sched_quantum_chunks` — chunk dispatches one scheduled job",
        "  runs before the next tenant is considered (default `4`,",
        "  must be >= 1).  Smaller quanta interleave more fairly at the",
        "  cost of more snapshot/rebuild churn when tenants exceed the",
        "  residency cap.  Runtime-only.",
        "- `sched_policy` — `round_robin` (default; aliases `rr`) or",
        "  `fair` (aliases `fair_share`, `deficit`): `fair` picks the",
        "  tenant with the least measured device-seconds per unit",
        "  weight, giving weighted proportional shares (Jain index in",
        "  the `sched_summary` record).  Runtime-only.",
        "- `sched_max_jobs` — resident-tenant cap (default `8`, must be",
        "  >= 1): beyond it the scheduler preempts the least-recently",
        "  sliced tenant to a byte-exact snapshot before admitting the",
        "  next slice's owner.  Admission also enforces the working-set",
        "  budget (`estimate_working_set` vs 90% of the device HBM,",
        "  the out-of-core `admit_fraction` convention).  Runtime-only.",
        "- `sched_health_out` — stream the scheduler-health JSONL there",
        "  (schema `lightgbm_tpu.health/v1`, kinds `sched_start`/",
        "  `sched_admit`/`sched_slice`/`sched_preempt_job`/`job_done`/",
        "  `sched_summary`).  Tail it with `tools/sched_monitor.py`.",
        "  Runtime-only.  See docs/SCHEDULING.md.",
        "",
    ]
    return "\n".join(lines)


def main():
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "PARAMETERS.md")
    text = generate()
    if "--check" in sys.argv:
        current = (open(out_path).read()
                   if os.path.exists(out_path) else "")
        if current != text:
            print("docs/PARAMETERS.md is stale; regenerate with "
                  "python tools/gen_params_doc.py")
            sys.exit(1)
        print("docs/PARAMETERS.md is current")
        return
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as fh:
        fh.write(text)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
