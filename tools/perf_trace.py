"""Capture a jax-profiler trace of segment-grower iterations and print a
per-op device-time breakdown from the xplane protobuf.

Usage: python tools/perf_trace.py [rows] [leaves]
"""

import glob
import os
import sys
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_500_000
L = int(sys.argv[2]) if len(sys.argv) > 2 else 255
TRACE_DIR = "/tmp/lgbtpu_trace"


def capture():
    import jax
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.core.dataset import TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objective import create_objective

    rng = np.random.RandomState(42)
    X = rng.normal(size=(N, 28)).astype(np.float32)
    y = (2 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
         + rng.normal(size=N) * 0.5 > 0).astype(np.float64)
    cfg = Config(objective="binary", num_leaves=L, max_bin=63,
                 learning_rate=0.1, min_sum_hessian_in_leaf=100.0,
                 verbosity=-1)
    ds = TpuDataset.from_numpy(X, y, config=cfg)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    booster = GBDT(cfg, ds, obj)
    for _ in range(2):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_score)
    jax.profiler.start_trace(TRACE_DIR)
    for _ in range(2):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_score)
    jax.profiler.stop_trace()


def summarize():
    from tensorboard_plugin_profile.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(TRACE_DIR, "**", "*.xplane.pb"),
                      recursive=True)
    assert paths, f"no xplane under {TRACE_DIR}"
    path = max(paths, key=os.path.getmtime)
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as fh:
        xs.ParseFromString(fh.read())
    for plane in xs.planes:
        if "TPU" not in plane.name and "tpu" not in plane.name.lower():
            continue
        tot = defaultdict(float)
        cnt = defaultdict(int)
        for line in plane.lines:
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                tot[name] += ev.duration_ps / 1e12
                cnt[name] += 1
        items = sorted(tot.items(), key=lambda kv: -kv[1])
        total = sum(tot.values())
        print(f"== plane {plane.name}: lines={len(plane.lines)} "
              f"total={total:.3f}s (2 iters; includes overlap)")
        for name, sec in items[:40]:
            print(f"  {sec:8.3f}s x{cnt[name]:<7} {name[:110]}")


if __name__ == "__main__":
    capture()
    summarize()
