"""Parse a jax-profiler xplane dump into top-op self-time table.

Usage: python tools/parse_profile.py <logdir>
"""
import glob
import json
import sys


def main():
    logdir = sys.argv[1]
    paths = sorted(glob.glob(logdir + "/**/*.xplane.pb", recursive=True))
    if not paths:
        print("no xplane.pb under", logdir)
        return
    path = paths[-1]
    from tensorboard_plugin_profile.convert import raw_to_tool_data
    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [path], "framework_op_stats^", {"tqx": "out:csv"})
    if isinstance(data, bytes):
        data = data.decode()
    lines = data.splitlines()
    print(lines[0])
    for ln in lines[1:40]:
        print(ln)


if __name__ == "__main__":
    main()
