"""Round-6 unattended on-chip measurement plan.

No backend was reachable while the round-6 variants were built; every
kernel change (packed accumulator, round-carry leaf-hist staging,
one-hot build alternatives, VMEM auto-limit) is interpret-validated
only.  The moment the chip answers, this driver runs the full A/B
ladder and appends everything to ONCHIP_LOG.md.  Nothing flips to
default until the numbers from this plan land in PERF_NOTES.md.

Ordered by value-per-chip-minute:

  1. kernel self-checks on REAL hardware — every auto-gate
     (fused route, packed acc, one-hot gather/twolevel, staging) must
     lower and match on-device; interpret-green is not lowering-green
     (ONCHIP_LOG round 4).  This also exercises the auto-sized
     vmem_limit_bytes on every fused compile.
  2. bench.py FIRST (the scoreboard; a short window must capture this)
  3. frontier defaults probe at 10.5M — validates the auto-sized VMEM
     limit at the calibration shape (K=16/F=28/rb=32768: estimator
     says 18 MB need -> 36 MB limit vs the old hand-set 64 MB; watch
     for Mosaic "scoped vmem" aborts, and the hist/vmem_limit_bytes
     gauge in the seg-stats print)
  4. packed-accumulator A/B (PACKED_ACC force vs 0, frontier + strict;
     gate: hist-pass time down AND train_auc within 1e-3 of the off leg)
  5. round-carry staging A/B (HIST_STAGE force vs 0, frontier only —
     bit-identical by construction, so wall is the whole verdict)
  6. one-hot build A/B (ONEHOT_BUILD gather!/twolevel! vs iota; "!"
     bypasses the self-check so a compile failure is loud here rather
     than silently falling back; twolevel needs power-of-two num_bins —
     max_bin=63 gives B=64, so the leg is real)
  7. in-scan eval chunked A/B re-run ON TPU (PR 7's fetch 32 -> 4; the
     CPU numbers in PERF_NOTES are the honest dispatch-vs-compute A/B,
     not the TPU win — this step replaces that caveat)
  8. bench_suite spill_ab ON TPU (PR 9's resident-vs-spill A/B; current
     trajectory records are CPU-fallback only).  bench_suite appends
     the trajectory record itself, including the new dispatch_labels /
     hist_pass_mean_s fields tools/bench_gate.py latency-gates.

Usage:
    python tools/onchip_r6.py          # run everything now
    python tools/onchip_r6.py --wait   # poll until the chip answers
    python tools/onchip_r6.py --if-up  # exit fast when the chip is down
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from onchip import PY, REPO, chip_up, log, run_step, wait_for_chip  # noqa: E402

PROBE_SHAPE = "10500000,255,1,3"     # HIGGS-scale headline shape
PROBE_SHAPE_SHORT = "10500000,255,1,2"

# In-scan eval A/B at TPU scale: same metric/leaves as the CPU A/B in
# PERF_NOTES ("In-scan eval" section) but 2M train / 200k valid rows so
# the per-iteration fetch actually costs device time.  Prints wall
# s/iter and transfer/fetch_calls for chunk=1 vs chunk=8 — the two
# numbers that replace the "CPU wall honest" caveat.
EVAL_AB = r"""
import time
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.telemetry import TELEMETRY

rng = np.random.RandomState(7)
def gen(n):
    X = rng.normal(size=(n, 20)).astype(np.float32)
    y = X[:, 0] * 2.0 + X[:, 1] - X[:, 2] * X[:, 3] \
        + rng.normal(size=n).astype(np.float32) * 0.1
    return X, y.astype(np.float64)
X, y = gen(2_000_000)
Xv, yv = gen(200_000)
for chunk in (1, 8):
    params = {"objective": "regression", "metric": "l2",
              "num_leaves": 31, "learning_rate": 0.1, "verbosity": -1,
              "tpu_boost_chunk": chunk}
    # warm-up run excludes compile from the measured wall
    lgb.train(params, lgb.Dataset(X, y), num_boost_round=4,
              valid_sets=[lgb.Dataset(Xv, yv)], valid_names=["v"],
              verbose_eval=False)
    TELEMETRY.reset()
    t0 = time.time()
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=32,
                    valid_sets=[lgb.Dataset(Xv, yv)], valid_names=["v"],
                    verbose_eval=False)
    wall = time.time() - t0
    c = TELEMETRY.stats()["counters"]
    print(f"EVAL_AB chunk={chunk} wall={wall:.2f}s "
          f"per_iter={wall / 32:.4f}s "
          f"fetch_calls={int(c.get('transfer/fetch_calls', 0))} "
          f"eval_fetch_calls={int(c.get('transfer/eval_fetch_calls', 0))}",
          flush=True)
"""


def main():
    if "--wait" in sys.argv:
        if not wait_for_chip(max_wait_s=10 * 3600):
            log("r6 probe: backend never came up; giving up")
            sys.exit(3)
        log("r6 probe: backend UP — running plan r6")
    elif not chip_up():
        if "--if-up" in sys.argv:
            print("backend down; skipping (--if-up)")
            sys.exit(3)
        log("r6 probe: backend DOWN; proceeding anyway")
    else:
        log("r6 probe: backend UP — running plan r6")

    probe = os.path.join(REPO, "tools", "perf_probe.py")
    bench = os.path.join(REPO, "bench.py")
    suite = os.path.join(REPO, "bench_suite.py")

    # 1. every kernel-variant self-check on real hardware (the same
    # entry point verify_t1.sh --with-kernel-checks runs on interpret)
    run_step("r6 kernel self-checks on chip", [PY, "-c", (
        "import sys;"
        "from lightgbm_tpu.ops.pallas_histogram import "
        "run_kernel_self_checks;"
        "sys.exit(run_kernel_self_checks())")], 1800)

    # 2. the scoreboard
    run_step("r6 bench (first)", [PY, bench], 9000)

    # 3. VMEM auto-limit validation at the calibration shape (frontier
    # K=16/F=28/rb=32768: the seg-stats print carries the gauge)
    run_step("r6 frontier defaults 10.5M (auto-VMEM)",
             [PY, probe, PROBE_SHAPE], 2400,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_IMPL": "frontier"})

    # 4. packed-accumulator A/B — force vs off, both growers.  "force"
    # bypasses the self-check so a lowering failure aborts loudly
    # instead of silently measuring the off leg.
    for impl in ("frontier", "auto"):
        tag = impl if impl != "auto" else "strict"
        run_step(f"r6 {tag} PACKED_ACC=force 10.5M",
                 [PY, probe, PROBE_SHAPE], 2400,
                 {"LIGHTGBM_TPU_SEG_STATS": "1",
                  "LIGHTGBM_TPU_IMPL": impl,
                  "LIGHTGBM_TPU_PACKED_ACC": "force"})
        run_step(f"r6 {tag} PACKED_ACC=0 10.5M",
                 [PY, probe, PROBE_SHAPE_SHORT], 2400,
                 {"LIGHTGBM_TPU_SEG_STATS": "1",
                  "LIGHTGBM_TPU_IMPL": impl,
                  "LIGHTGBM_TPU_PACKED_ACC": "0"})

    # 5. round-carry staging A/B (frontier only; serial path)
    run_step("r6 frontier HIST_STAGE=force 10.5M",
             [PY, probe, PROBE_SHAPE], 2400,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_IMPL": "frontier",
              "LIGHTGBM_TPU_HIST_STAGE": "force"})
    run_step("r6 frontier HIST_STAGE=0 10.5M",
             [PY, probe, PROBE_SHAPE_SHORT], 2400,
             {"LIGHTGBM_TPU_SEG_STATS": "1",
              "LIGHTGBM_TPU_IMPL": "frontier",
              "LIGHTGBM_TPU_HIST_STAGE": "0"})

    # 6. one-hot build A/B (strict grower so the ~18 ms one-hot share
    # of the ~27 ms pass — PERF_NOTES round 5 — is the denominator)
    for build in ("gather!", "twolevel!", "iota"):
        run_step(f"r6 strict ONEHOT_BUILD={build} 10.5M",
                 [PY, probe, PROBE_SHAPE_SHORT], 2400,
                 {"LIGHTGBM_TPU_SEG_STATS": "1",
                  "LIGHTGBM_TPU_ONEHOT_BUILD": build})

    # 7. in-scan eval chunked A/B on TPU (replaces the CPU-wall caveat)
    run_step("r6 in-scan eval A/B (chunk 1 vs 8, 2M rows)",
             [PY, "-c", EVAL_AB], 3600)

    # 8. spill A/B on TPU (appends its own trajectory record)
    run_step("r6 bench_suite spill_ab", [PY, suite, "spill_ab"], 4800)

    log("plan r6 complete")


if __name__ == "__main__":
    main()
