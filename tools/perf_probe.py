"""Perf probe: per-iteration time vs (num_rows, num_leaves) on the live
backend.  Confirms where segment-grower time goes: per-split overhead
(scales with L) vs data work (scales with N).  Usage:

    python tools/perf_probe.py "rows,leaves,warmup,measure" ...
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(n_rows: int, num_leaves: int, warmup: int, measure: int) -> None:
    import jax
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache()
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.core.dataset import TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objective import create_objective

    rng = np.random.RandomState(42)
    X = rng.normal(size=(n_rows, 28)).astype(np.float32)
    logit = 2.0 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
    y = (logit + rng.normal(size=n_rows) * 0.5 > 0).astype(np.float64)
    # LIGHTGBM_TPU_IMPL=segment|frontier|fused switches the grower for
    # on-chip A/B runs (frontier is the batched-MXU candidate)
    impl = os.environ.get("LIGHTGBM_TPU_IMPL", "auto")
    # LIGHTGBM_TPU_ROW_CHUNK overrides the auto row-block size for
    # block-granularity A/Bs (finer blocks = tighter confinement
    # intervals but more grid steps)
    row_chunk = int(os.environ.get("LIGHTGBM_TPU_ROW_CHUNK", "0"))
    # LIGHTGBM_TPU_FRONTIER_K overrides the frontier batch width (wide-K
    # + huge COMPACT_WASTE approximates sort-free level-ish growth)
    frontier_k = int(os.environ.get("LIGHTGBM_TPU_FRONTIER_K", "0"))
    # LIGHTGBM_TPU_GAIN_RATIO overrides tpu_frontier_gain_ratio (per-round
    # batching width: lower ratio = fewer/fuller rounds = less per-round
    # while-carry copy traffic, at some best-first-ordering cost)
    gain_ratio = os.environ.get("LIGHTGBM_TPU_GAIN_RATIO")
    cfg = Config(objective="binary", num_leaves=num_leaves, max_bin=63,
                 learning_rate=0.1, min_sum_hessian_in_leaf=100.0,
                 verbosity=-1, tpu_tree_impl=impl, tpu_row_chunk=row_chunk,
                 tpu_frontier_width=frontier_k,
                 **({"tpu_frontier_gain_ratio": float(gain_ratio)}
                    if gain_ratio is not None else {}))
    ds = TpuDataset.from_numpy(X, y, config=cfg)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    booster = GBDT(cfg, ds, obj)
    t0 = time.time()
    for _ in range(warmup):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_score)
    t_warm = time.time() - t0
    from lightgbm_tpu.utils.phase import GLOBAL_TIMER
    GLOBAL_TIMER.reset()
    t0 = time.time()
    for _ in range(measure):
        booster.train_one_iter()
    jax.block_until_ready(booster.train_score)
    per_iter = (time.time() - t0) / measure
    if booster._use_segment:
        ran = "frontier" if impl == "frontier" else "segment"
    else:
        ran = "fused"
    s = np.asarray(booster.train_score).ravel()[:n_rows]
    order = np.argsort(s, kind="stable")
    ranks = np.empty(n_rows)
    ranks[order] = np.arange(1, n_rows + 1)
    # midranks for ties (bench.py's correction: few distinct leaf-value
    # sums early on make naive ranks row-order-dependent)
    uniq, inv, cnt = np.unique(s, return_inverse=True, return_counts=True)
    rank_sum = np.zeros(len(uniq))
    np.add.at(rank_sum, inv, ranks)
    ranks = (rank_sum / cnt)[inv]
    n_pos = int((y > 0.5).sum())
    auc = ((ranks[y > 0.5].sum() - n_pos * (n_pos + 1) / 2)
           / (n_pos * (n_rows - n_pos)))
    print(f"PROBE rows={n_rows} leaves={num_leaves} impl={ran} "
          f"warmup={t_warm:.1f}s per_iter={per_iter:.4f}s "
          f"train_auc@{warmup + measure}it={auc:.5f}", flush=True)
    print("PROBE " + GLOBAL_TIMER.summary(), flush=True)


if __name__ == "__main__":
    for spec in sys.argv[1:]:
        r, l, w, m = (int(x) for x in spec.split(","))
        run(r, l, w, m)
