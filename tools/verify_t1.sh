#!/usr/bin/env bash
# Tier-1 verification gate — the EXACT command from ROADMAP.md, so
# builders and reviewers run the identical check.  Prints
# DOTS_PASSED=<n> (count of passing-test dots in the pytest progress
# lines) and exits with pytest's return code.
#
# Usage: bash tools/verify_t1.sh             (from anywhere; cd's to repo root)
#        bash tools/verify_t1.sh --with-gate (also run the perf-regression
#                                             gate's self-test afterwards —
#                                             covers the wall/HBM/quality
#                                             checks AND the measured
#                                             dispatch-latency gate)
#        bash tools/verify_t1.sh --serve-smoke (also run one tiny
#                                             bench_serve cell: trains a
#                                             toy model, pushes requests
#                                             through the compiled
#                                             micro-batching queue and
#                                             bit-checks vs Booster.predict;
#                                             then a ~2s open-loop loadgen
#                                             burst asserting the serve
#                                             health stream parses, the
#                                             coalescing window engages
#                                             under load, and every reply
#                                             stays bit-identical — plus
#                                             a hot-swap cell: 3 atomic
#                                             swaps under live traffic
#                                             with zero failed replies,
#                                             every reply bit-identical
#                                             to a live generation and
#                                             the flip pause p99 bounded;
#                                             writes no artifacts)
#        bash tools/verify_t1.sh --sched-smoke (also run the
#                                             multi-tenant scheduler
#                                             smoke: 3 jobs — binary,
#                                             multiclass, lambdarank —
#                                             time-sliced under the fair
#                                             policy in a temp dir, with
#                                             health-stream
#                                             well-formedness assertions;
#                                             writes no artifacts)
#        bash tools/verify_t1.sh --fleet-smoke (also run the fleet
#                                             observability smoke: a real
#                                             2-rank localhost CPU fleet
#                                             with periodic collective
#                                             window syncs, per-rank
#                                             Chrome traces merged onto
#                                             one skew-corrected timeline
#                                             by fleet_trace.py, the
#                                             all-streams fleet_monitor
#                                             view, and a
#                                             fleet_summary.json accepted
#                                             by bench_gate.py; then the
#                                             gate's own self-test;
#                                             writes no repo artifacts)
#        bash tools/verify_t1.sh --with-kernel-checks (also run every
#                                             kernel variant self-check —
#                                             fused route, fused-K
#                                             route+histogram, packed
#                                             accumulator, one-hot builds,
#                                             round-carry staging — on the
#                                             CPU interpret backend so CI
#                                             catches parity regressions;
#                                             on-chip runs catch lowering
#                                             drift the interpreter can't)
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "$1" = "--with-gate" ]; then
    python tools/bench_gate.py --self-test || exit 1
fi
if [ "$1" = "--serve-smoke" ]; then
    timeout -k 10 330 env BENCH_SKIP_TPU=1 python tools/bench_serve.py --smoke || exit 1
    timeout -k 10 330 env JAX_PLATFORMS=cpu python tools/loadgen.py --smoke || exit 1
fi
if [ "$1" = "--sched-smoke" ]; then
    timeout -k 10 330 env JAX_PLATFORMS=cpu python tools/submit_jobs.py --smoke || exit 1
fi
if [ "$1" = "--fleet-smoke" ]; then
    timeout -k 10 330 env JAX_PLATFORMS=cpu python tools/fleet_monitor.py --smoke || exit 1
    python tools/bench_gate.py --self-test || exit 1
fi
if [ "$1" = "--with-kernel-checks" ]; then
    timeout -k 10 330 env JAX_PLATFORMS=cpu python -c 'import sys; from lightgbm_tpu.ops.pallas_histogram import run_kernel_self_checks; sys.exit(run_kernel_self_checks())' || exit 1
fi
exit $rc
