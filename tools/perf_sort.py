"""Compare compaction strategies at HIGGS size: the current 13-operand
lax.sort vs sort-(key,index)-then-gather-payload.  All outputs reduced to
scalars before fetch (the tunnel makes large fetches look like seconds)."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_500_000
K = 5


def main():
    import jax
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache()
    import jax.numpy as jnp
    from jax import lax
    from lightgbm_tpu.ops.pallas_histogram import pack_channels, \
        pick_block_rows
    from lightgbm_tpu.models.grower_seg import (_pack_bins_words,
                                                _pack_w8_words)

    rb = pick_block_rows(28, 64, N)
    npad = -(-N // rb) * rb
    print(f"N={N} npad={npad} backend={jax.default_backend()}", flush=True)
    rng = np.random.RandomState(0)
    binsT = jnp.asarray(rng.randint(0, 64, size=(32, npad),
                                    dtype=np.int64).astype(np.uint8))
    w8 = pack_channels(jnp.asarray(rng.normal(size=npad).astype(np.float32)),
                       jnp.ones(npad, jnp.float32),
                       jnp.ones(npad, jnp.float32))
    lid0 = jnp.asarray(rng.randint(0, 256, size=npad).astype(np.int32))

    def timed(make_fn, label):
        f1 = jax.jit(make_fn(1))
        fK = jax.jit(make_fn(K))
        np.asarray(f1(binsT, w8, lid0)).sum()
        np.asarray(fK(binsT, w8, lid0)).sum()
        t0 = time.perf_counter(); np.asarray(f1(binsT, w8, lid0)).sum()
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter(); np.asarray(fK(binsT, w8, lid0)).sum()
        tK = time.perf_counter() - t0
        per = (tK - t1) / (K - 1)
        print(f"{label}: {per*1e3:.1f} ms/op (t1={t1*1e3:.0f} "
              f"tK={tK*1e3:.0f})", flush=True)

    def reshuffle(lid, i):
        # cheap pseudo-random re-keying so every chained sort does real work
        return ((lid * 1103515245 + i * 12345) & 0xFF).astype(jnp.int32)

    # current: sort keys + 13 payload operands
    def mk_full(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                ops = ((reshuffle(lid_c, i),) + tuple(_pack_bins_words(bT))
                       + tuple(_pack_w8_words(w))
                       + (jnp.arange(npad, dtype=jnp.int32),))
                return lax.sort(ops, num_keys=1, is_stable=True)[0]
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_full, "sort13")

    # candidate: sort (key, index) then one gather per payload tensor
    def mk_pair(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                keys = reshuffle(lid_c, i)
                _, perm = lax.sort((keys, jnp.arange(npad, dtype=jnp.int32)),
                                   num_keys=1, is_stable=True)
                b2 = jnp.take(bT, perm, axis=1)
                w2 = jnp.take(w, perm, axis=1)
                return lid_c + b2[0].astype(jnp.int32) + \
                    w2[4].astype(jnp.int32)
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_pair, "sort2+gather")

    # sort cost alone (2 operands)
    def mk_pair_only(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                keys = reshuffle(lid_c, i)
                s, perm = lax.sort(
                    (keys, jnp.arange(npad, dtype=jnp.int32)),
                    num_keys=1, is_stable=True)
                return lid_c + s + perm
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_pair_only, "sort2_only")

    # gather cost alone
    def mk_gather(reps):
        def fn(bT, w, lid):
            def body(i, acc):
                perm = (jnp.arange(npad, dtype=jnp.int32) * 7 + i) % npad
                b2 = jnp.take(bT, perm, axis=1)
                w2 = jnp.take(w, perm, axis=1)
                return acc + b2[0].astype(jnp.int32) + \
                    w2[4].astype(jnp.int32)
            return jnp.sum(lax.fori_loop(0, reps, body, lid))
        return fn
    timed(mk_gather, "gather_only")


main()
