"""Round-7 unattended on-chip measurement plan: the fused-K ladder.

PR 16 fuses the frontier round's K route updates and ALL 2K child
histograms into one Pallas pass (``histogram_frontier_fusedk``),
retiring both the standalone route passes and the per-round
``[L, G, B, 3]`` leaf_hist gather/scatter.  Interpret-validated only so
far — the env gate stays OFF until the A/B numbers from this plan land
in PERF_NOTES.md (same no-default-flip rule every r6 variant followed).

Every bench_suite cell below appends its own ``device_timing`` measured
record to BENCH_TRAJECTORY.jsonl: DEVICE_TIMING=1 turns on the synced
dispatch timers, and SUITE_CONFIG_TAG gives each env cell its own
config series so tools/bench_gate.py's per-config latency baselines
never mix a forced variant with the defaults.  The fused rounds dispatch
under the ``grow/frontier[fused_hist_k{K}]`` label ("hist" in the name
keys the suite's hist-pass rollup to it).

Ordered by value-per-chip-minute:

  1. kernel self-checks on REAL hardware — run_kernel_self_checks now
     includes ``fused_k``; interpret-green is not lowering-green
     (ONCHIP_LOG round 4), and the fused-K pass carries the 2K-wide
     accumulator the auto VMEM limit must absorb.
  2. fused-K force-vs-off A/B at K ∈ {4, 8, 16} — the headline ladder.
     "force" bypasses the self-check memo so a lowering failure aborts
     loudly instead of silently measuring the off leg; the off leg pins
     FUSED_K=0 so auto can never flip mid-ladder.
  3. round-carry-staging reference cell (FUSED_K=0 HIST_STAGE=force,
     K=8) — the best unfused variant from r6, measured in the same
     session so the fused-vs-staged comparison shares a machine state.
  4. fused-K x staging combined cell (both forced, K=8) — fused-K
     disables staging at build time (nothing left to stage); this cell
     confirms the combination degrades to pure fused-K rather than
     compounding, and its seg-stats print records the decision.

Usage:
    python tools/onchip_r7.py          # run everything now
    python tools/onchip_r7.py --wait   # poll until the chip answers
    python tools/onchip_r7.py --if-up  # exit fast when the chip is down
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from onchip import PY, REPO, chip_up, log, run_step, wait_for_chip  # noqa: E402

SUITE_CONFIG = "goss_regression"   # frontier-eligible suite config with
#                                    a CPU-fallback tier, so the whole
#                                    ladder also runs end-to-end off-chip

BASE_ENV = {
    "LIGHTGBM_TPU_DEVICE_TIMING": "1",
    "LIGHTGBM_TPU_SEG_STATS": "1",
    "LIGHTGBM_TPU_IMPL": "frontier",
}


def suite_cell(name: str, tag: str, env: dict, timeout_s: int = 2400):
    suite = os.path.join(REPO, "bench_suite.py")
    run_step(name, [PY, suite, SUITE_CONFIG], timeout_s,
             dict(BASE_ENV, SUITE_CONFIG_TAG=tag, **env))


def main():
    if "--wait" in sys.argv:
        if not wait_for_chip(max_wait_s=10 * 3600):
            log("r7 probe: backend never came up; giving up")
            sys.exit(3)
        log("r7 probe: backend UP — running plan r7")
    elif not chip_up():
        if "--if-up" in sys.argv:
            print("backend down; skipping (--if-up)")
            sys.exit(3)
        log("r7 probe: backend DOWN; proceeding anyway (CPU fallback)")
    else:
        log("r7 probe: backend UP — running plan r7")

    # 1. every kernel-variant self-check (now including fused_k) on the
    # live backend — same entry point verify_t1.sh --with-kernel-checks
    # runs on interpret
    run_step("r7 kernel self-checks on chip", [PY, "-c", (
        "import sys;"
        "from lightgbm_tpu.ops.pallas_histogram import "
        "run_kernel_self_checks;"
        "sys.exit(run_kernel_self_checks())")], 1800)

    # 2. fused-K force-vs-off ladder.  The off leg pins FUSED_K=0 (auto
    # could flip once numbers land); both legs pin the frontier width so
    # the cells measure the K they name.
    for k in (4, 8, 16):
        suite_cell(f"r7 fused-K=force K={k}", f"fusedk{k}_force",
                   {"LIGHTGBM_TPU_FUSED_K": "force",
                    "LIGHTGBM_TPU_FRONTIER_K": str(k)})
        suite_cell(f"r7 fused-K=0 K={k}", f"fusedk{k}_off",
                   {"LIGHTGBM_TPU_FUSED_K": "0",
                    "LIGHTGBM_TPU_FRONTIER_K": str(k)})

    # 3. round-carry staging reference (the r6 winner candidate) in the
    # same session as the fused cells it is compared against
    suite_cell("r7 staged-unfused reference K=8", "stage_ref_k8",
               {"LIGHTGBM_TPU_FUSED_K": "0",
                "LIGHTGBM_TPU_HIST_STAGE": "force",
                "LIGHTGBM_TPU_FRONTIER_K": "8"})

    # 4. combined cell: fused-K wins the conflict at build time (staging
    # has nothing to stage when no round reads leaf_hist) — confirm the
    # combination degrades to pure fused-K instead of compounding
    suite_cell("r7 fused-K x HIST_STAGE combined K=8", "fusedk8_stage",
               {"LIGHTGBM_TPU_FUSED_K": "force",
                "LIGHTGBM_TPU_HIST_STAGE": "force",
                "LIGHTGBM_TPU_FRONTIER_K": "8"})

    log("plan r7 complete")


if __name__ == "__main__":
    main()
