"""Perf-regression sentinel over BENCH_TRAJECTORY.jsonl.

BENCH_TRAJECTORY.jsonl (appended by bench_suite.py / bench.py rounds)
is the machine-readable perf trajectory across PRs: one digest line per
run with wall value, peak HBM, quality gate and FLOP estimates.  This
tool turns the trailing history into a GATE instead of a log: for each
config, the newest record is compared against the median of the
previous ``--window`` records, and the gate fails (exit 1) when

  * wall time regresses more than ``--wall-tol`` (default +15%),
  * peak HBM regresses more than ``--hbm-tol`` (default +20%),
  * the quality gate flips from held to failed,
  * measured dispatch latency (``dispatch_mean_s``, recorded by runs
    with ``device_timing=`` on) regresses more than ``--latency-tol``
    (default +20%), or
  * serve tail latency (``p99_s``, recorded by bench_serve.py) regresses
    more than ``--latency-tol`` over the trailing median,
  * the drift gate flips — ``drift_ok`` (recorded by loadgen --shift
    runs, true when the drift plane's verdict matched expectation)
    goes from held to failed — or ``psi_max`` regresses more than
    ``--psi-tol`` over the trailing median while sitting above the
    absolute noise floor (0.1 PSI; below it, sampling jitter dominates
    and the ratio gate stays silent),
  * the hot-swap flip pause (``swap_pause_p99_s``, recorded by loadgen
    --swap cells) regresses more than ``--latency-tol`` over the
    trailing median, or the shed rate (``shed_rate``) regresses more
    than ``--latency-tol`` — including shedding APPEARING where the
    trailing history shed nothing.

Serve records (bench_serve.py) carry ``qps``/``p50_s``/``p99_s`` and no
training ``value``/``unit``/``peak_hbm_bytes`` — every gate skips fields
a record does not have, so mixed trajectories gate cleanly.

A missing/empty trajectory, a config with no prior history, or records
without comparable fields all PASS with a "no history" notice — the
gate never blocks the first benchmark of a new config.

Usage:
  python tools/bench_gate.py                     # repo trajectory
  python tools/bench_gate.py --path X.jsonl --window 8 --wall-tol 0.10
  python tools/bench_gate.py --self-test         # fast CI smoke
  python tools/bench_gate.py --fleet-summary fleet_summary.json

``--fleet-summary`` gates a tools/fleet_monitor.py rollup instead of
the trajectory: schema pin, per-rank wait fractions in [0, 1],
straggler histogram consistency, per-subsystem fault counts.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATH = os.path.join(REPO, "BENCH_TRAJECTORY.jsonl")

FLEET_SUMMARY_SCHEMA = "lightgbm_tpu.fleet_summary/v1"


def load(path):
    """Trajectory records, oldest first.  Null-tolerant: a missing or
    empty file is just an empty history; torn lines are skipped."""
    records = []
    if not os.path.exists(path):
        return records
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _median(values):
    vals = sorted(values)
    n = len(vals)
    if n == 0:
        return None
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def _config_of(rec):
    return rec.get("config") or rec.get("metric") or "?"


PSI_NOISE_FLOOR = 0.1


def evaluate(records, window=5, wall_tol=0.15, hbm_tol=0.20,
             latency_tol=0.20, psi_tol=0.50):
    """(failures, notes) over the trajectory.  The newest record of each
    config is judged against the median of up to ``window`` prior
    records of the same config; everything older informs, never gates."""
    failures, notes = [], []
    if not records:
        notes.append("no history: trajectory is empty or absent — pass")
        return failures, notes
    by_config = {}
    for rec in records:
        by_config.setdefault(_config_of(rec), []).append(rec)
    for config, recs in sorted(by_config.items()):
        newest, history = recs[-1], recs[:-1][-window:]
        if not history:
            notes.append(f"{config}: no history (first record) — pass")
            continue
        # quality flip: regressing from held quality is a failure even
        # when the timing looks fine
        held_before = any(r.get("quality_ok") for r in history)
        if held_before and newest.get("quality_ok") is False:
            failures.append(f"{config}: quality gate flipped to FAILED "
                            f"(held in trailing history)")
        value = newest.get("value")
        base_vals = [r["value"] for r in history
                     if isinstance(r.get("value"), (int, float))
                     and r["value"] > 0
                     and r.get("unit") == newest.get("unit")]
        base = _median(base_vals)
        if (isinstance(value, (int, float)) and value > 0
                and base is not None):
            ratio = value / base
            line = (f"{config}: {newest.get('metric', 'value')} "
                    f"{value:g}{newest.get('unit', '')} vs median "
                    f"{base:g} ({ratio - 1.0:+.1%})")
            if ratio > 1.0 + wall_tol:
                failures.append(f"{config}: wall {value:g}"
                                f"{newest.get('unit', '')} regressed "
                                f"{ratio - 1.0:+.1%} over median "
                                f"{base:g} (tol +{wall_tol:.0%})")
            else:
                notes.append(line + " — ok")
        else:
            notes.append(f"{config}: no comparable wall history — pass")
        hbm = newest.get("peak_hbm_bytes")
        hbm_base = _median([r["peak_hbm_bytes"] for r in history
                            if isinstance(r.get("peak_hbm_bytes"),
                                          (int, float))
                            and r["peak_hbm_bytes"] > 0])
        if (isinstance(hbm, (int, float)) and hbm > 0
                and hbm_base is not None):
            if hbm / hbm_base > 1.0 + hbm_tol:
                failures.append(
                    f"{config}: peak HBM {hbm:.0f}B regressed "
                    f"{hbm / hbm_base - 1.0:+.1%} over median "
                    f"{hbm_base:.0f}B (tol +{hbm_tol:.0%})")
        # measured dispatch latency (device_timing runs only): wall time
        # can hide a slower dispatch behind async pipelining — the
        # measured mean cannot
        lat = newest.get("dispatch_mean_s")
        lat_base = _median([r["dispatch_mean_s"] for r in history
                            if isinstance(r.get("dispatch_mean_s"),
                                          (int, float))
                            and r["dispatch_mean_s"] > 0])
        if (isinstance(lat, (int, float)) and lat > 0
                and lat_base is not None):
            if lat / lat_base > 1.0 + latency_tol:
                failures.append(
                    f"{config}: dispatch latency {lat * 1e3:.3f}ms "
                    f"regressed {lat / lat_base - 1.0:+.1%} over median "
                    f"{lat_base * 1e3:.3f}ms (tol +{latency_tol:.0%})")
            else:
                notes.append(f"{config}: dispatch latency "
                             f"{lat * 1e3:.3f}ms vs median "
                             f"{lat_base * 1e3:.3f}ms — ok")
        # histogram-pass latency (records with per-label dispatch
        # timing): the hist kernels are the iteration's dominant cost
        # post-route-window, so a regression here can hide inside a
        # steady wall when other phases happen to improve
        hp = newest.get("hist_pass_mean_s")
        hp_base = _median([r["hist_pass_mean_s"] for r in history
                           if isinstance(r.get("hist_pass_mean_s"),
                                         (int, float))
                           and r["hist_pass_mean_s"] > 0])
        if (isinstance(hp, (int, float)) and hp > 0
                and hp_base is not None):
            if hp / hp_base > 1.0 + latency_tol:
                failures.append(
                    f"{config}: hist pass {hp * 1e3:.3f}ms regressed "
                    f"{hp / hp_base - 1.0:+.1%} over median "
                    f"{hp_base * 1e3:.3f}ms (tol +{latency_tol:.0%})")
            else:
                notes.append(f"{config}: hist pass {hp * 1e3:.3f}ms vs "
                             f"median {hp_base * 1e3:.3f}ms — ok")
        # serve tail latency (bench_serve.py records): p99 is the
        # service-level promise, so it gates where mean would forgive a
        # fat tail
        p99 = newest.get("p99_s")
        p99_base = _median([r["p99_s"] for r in history
                            if isinstance(r.get("p99_s"), (int, float))
                            and r["p99_s"] > 0])
        if (isinstance(p99, (int, float)) and p99 > 0
                and p99_base is not None):
            if p99 / p99_base > 1.0 + latency_tol:
                failures.append(
                    f"{config}: serve p99 {p99 * 1e3:.3f}ms regressed "
                    f"{p99 / p99_base - 1.0:+.1%} over median "
                    f"{p99_base * 1e3:.3f}ms (tol +{latency_tol:.0%})")
            else:
                notes.append(f"{config}: serve p99 {p99 * 1e3:.3f}ms vs "
                             f"median {p99_base * 1e3:.3f}ms — ok")
        # drift gate (loadgen --shift records): drift_ok carries the
        # end-to-end verdict (shifted sweep detected, control clean,
        # replies bit-identical) — a flip from held is a failure like a
        # quality flip.  psi_max additionally ratio-gates against its
        # trailing median, but only above an absolute noise floor:
        # small-PSI windows move multiplicatively with sampling jitter
        # and would flap the gate.
        drift_held = any(r.get("drift_ok") for r in history)
        if drift_held and newest.get("drift_ok") is False:
            failures.append(f"{config}: drift gate flipped to FAILED "
                            f"(held in trailing history)")
        psi = newest.get("psi_max")
        psi_base = _median([r["psi_max"] for r in history
                            if isinstance(r.get("psi_max"), (int, float))
                            and r["psi_max"] > 0])
        if (isinstance(psi, (int, float)) and psi > 0
                and psi_base is not None):
            if (psi > PSI_NOISE_FLOOR
                    and psi / psi_base > 1.0 + psi_tol):
                failures.append(
                    f"{config}: psi_max {psi:.3f} regressed "
                    f"{psi / psi_base - 1.0:+.1%} over median "
                    f"{psi_base:.3f} (tol +{psi_tol:.0%}, floor "
                    f"{PSI_NOISE_FLOOR:g})")
            else:
                notes.append(f"{config}: psi_max {psi:.3f} vs median "
                             f"{psi_base:.3f} — ok")
        # hot-swap cells (loadgen --swap): the flip pause p99 is the
        # zero-downtime promise in seconds — it gates like a latency
        pause = newest.get("swap_pause_p99_s")
        pause_base = _median([r["swap_pause_p99_s"] for r in history
                              if isinstance(r.get("swap_pause_p99_s"),
                                            (int, float))
                              and r["swap_pause_p99_s"] > 0])
        if (isinstance(pause, (int, float)) and pause > 0
                and pause_base is not None):
            if pause / pause_base > 1.0 + latency_tol:
                failures.append(
                    f"{config}: swap pause p99 {pause * 1e3:.3f}ms "
                    f"regressed {pause / pause_base - 1.0:+.1%} over "
                    f"median {pause_base * 1e3:.3f}ms "
                    f"(tol +{latency_tol:.0%})")
            else:
                notes.append(f"{config}: swap pause p99 "
                             f"{pause * 1e3:.3f}ms vs median "
                             f"{pause_base * 1e3:.3f}ms — ok")
        # shed rate: a ratio gate where the cell historically shed, and
        # an appearance gate where it never did — a queue that starts
        # shedding at an unchanged arrival rate is a capacity regression
        shed = newest.get("shed_rate")
        shed_hist = [r["shed_rate"] for r in history
                     if isinstance(r.get("shed_rate"), (int, float))]
        if isinstance(shed, (int, float)) and shed_hist:
            shed_base = _median(shed_hist)
            if shed_base > 0 and shed / shed_base > 1.0 + latency_tol:
                failures.append(
                    f"{config}: shed rate {shed:.4f} regressed "
                    f"{shed / shed_base - 1.0:+.1%} over median "
                    f"{shed_base:.4f} (tol +{latency_tol:.0%})")
            elif shed_base == 0 and shed > 0:
                failures.append(
                    f"{config}: shedding appeared (rate {shed:.4f}) "
                    f"where the trailing history shed nothing")
            else:
                notes.append(f"{config}: shed rate {shed:.4f} vs "
                             f"median {shed_base:.4f} — ok")
    return failures, notes


def gate(path, window=5, wall_tol=0.15, hbm_tol=0.20, latency_tol=0.20,
         psi_tol=0.50, out=sys.stdout):
    failures, notes = evaluate(load(path), window, wall_tol, hbm_tol,
                               latency_tol, psi_tol)
    for note in notes:
        out.write(f"bench_gate: {note}\n")
    for failure in failures:
        out.write(f"bench_gate: FAIL {failure}\n")
    out.write(f"bench_gate: {'FAIL' if failures else 'PASS'} "
              f"({len(failures)} regression(s), {path})\n")
    return 1 if failures else 0


def validate_fleet_summary(summary):
    """Structural gate over a tools/fleet_monitor.py
    ``fleet_summary.json``: returns a list of problems (empty = valid).
    The CI fleet-smoke leg feeds its freshly-written summary through
    this, so a malformed v6 rollup fails the build, not the reader."""
    problems = []
    if not isinstance(summary, dict):
        return ["fleet summary is not a JSON object"]
    if summary.get("schema") != FLEET_SUMMARY_SCHEMA:
        problems.append(f"schema {summary.get('schema')!r} != "
                        f"{FLEET_SUMMARY_SCHEMA!r}")
    streams = summary.get("streams")
    if not isinstance(streams, dict) or not streams:
        problems.append("streams section missing or empty")
    else:
        for name, view in streams.items():
            if not isinstance(view, dict) or "status" not in view:
                problems.append(f"stream {name}: malformed view")
            elif not isinstance(view.get("records"), int) \
                    or view["records"] < 0:
                problems.append(f"stream {name}: bad record count "
                                f"{view.get('records')!r}")
    per_rank = summary.get("per_rank", {})
    if not isinstance(per_rank, dict):
        problems.append("per_rank is not an object")
    else:
        for rank, slot in per_rank.items():
            frac = slot.get("wait_fraction") \
                if isinstance(slot, dict) else None
            if not isinstance(frac, (int, float)) \
                    or not 0.0 <= frac <= 1.0:
                problems.append(f"rank {rank}: wait_fraction "
                                f"{frac!r} outside [0, 1]")
            for key in ("wait_s", "work_s"):
                v = slot.get(key) if isinstance(slot, dict) else None
                if not isinstance(v, (int, float)) or v < 0:
                    problems.append(f"rank {rank}: {key} {v!r} "
                                    f"negative or missing")
    hist = summary.get("straggler_hist", {})
    if not isinstance(hist, dict) or any(
            not isinstance(n, int) or n < 1 for n in hist.values()):
        problems.append("straggler_hist counts must be positive ints")
    elif isinstance(summary.get("windows"), int) \
            and sum(hist.values()) > summary["windows"]:
        problems.append("straggler_hist exceeds the window count")
    faults = summary.get("faults", {})
    if not isinstance(faults, dict) or any(
            not isinstance(n, int) or n < 0 for n in faults.values()):
        problems.append("faults section counts must be ints >= 0")
    if not isinstance(summary.get("complete"), bool):
        problems.append("complete flag missing or not a bool")
    return problems


def gate_fleet_summary(path, out=sys.stdout):
    try:
        with open(path) as fh:
            summary = json.load(fh)
    except (OSError, ValueError) as e:
        out.write(f"bench_gate: FAIL unreadable fleet summary "
                  f"{path}: {e}\n")
        return 1
    problems = validate_fleet_summary(summary)
    for p in problems:
        out.write(f"bench_gate: FAIL fleet summary: {p}\n")
    out.write(f"bench_gate: fleet summary "
              f"{'FAIL' if problems else 'PASS'} ({path})\n")
    return 1 if problems else 0


def self_test():
    """Fast smoke of the gate logic (no files, no history mutation)."""
    hist = [{"config": "c", "value": 10.0 + 0.1 * i, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 1000,
             "dispatch_mean_s": 0.010 + 0.0001 * i}
            for i in range(4)]

    def verdict(newest):
        failures, _ = evaluate(hist + [newest])
        return bool(failures)

    checks = [
        ("empty history passes", evaluate([]) == ([], [
            "no history: trajectory is empty or absent — pass"])),
        ("first record passes",
         not evaluate([{"config": "new", "value": 1.0, "unit": "s"}])[0]),
        ("steady wall passes", not verdict(
            {"config": "c", "value": 10.2, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 1000})),
        ("wall regression fails", verdict(
            {"config": "c", "value": 20.0, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 1000})),
        ("hbm regression fails", verdict(
            {"config": "c", "value": 10.2, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 5000})),
        ("quality flip fails", verdict(
            {"config": "c", "value": 10.2, "unit": "s",
             "quality_ok": False, "peak_hbm_bytes": 1000})),
        ("null fields pass", not verdict(
            {"config": "c", "value": None, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": None})),
        ("steady dispatch latency passes", not verdict(
            {"config": "c", "value": 10.2, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 1000,
             "dispatch_mean_s": 0.0102})),
        ("dispatch latency regression fails", verdict(
            {"config": "c", "value": 10.2, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 1000,
             "dispatch_mean_s": 0.020})),
        ("timing-off record passes latency gate", not verdict(
            {"config": "c", "value": 10.2, "unit": "s",
             "quality_ok": True, "peak_hbm_bytes": 1000,
             "dispatch_mean_s": None})),
    ]
    hhist = [{"config": "h", "value": 1.0, "unit": "s/iter",
              "quality_ok": True, "hist_pass_mean_s": 0.0124 + 0.0001 * i}
             for i in range(4)]

    def hverdict(newest):
        failures, _ = evaluate(hhist + [newest])
        return bool(failures)

    checks += [
        ("steady hist pass passes", not hverdict(
            {"config": "h", "value": 1.0, "unit": "s/iter",
             "quality_ok": True, "hist_pass_mean_s": 0.0126})),
        ("hist pass regression fails", hverdict(
            {"config": "h", "value": 1.0, "unit": "s/iter",
             "quality_ok": True, "hist_pass_mean_s": 0.020})),
        ("hist-field-free record passes hist gate", not hverdict(
            {"config": "h", "value": 1.0, "unit": "s/iter",
             "quality_ok": True, "hist_pass_mean_s": None})),
    ]
    # fused-K ladder records (tools/onchip_r7.py): the fused rounds
    # dispatch under the grower's own label, so hist_pass_label takes
    # the "grow/frontier[fused_hist_kK]" shape, and SUITE_CONFIG_TAG
    # makes the cell its own config series — the gate must baseline the
    # tagged series against itself, never the untagged defaults
    fkhist = [{"config": "goss_regression+fusedk8_force", "value": 30.0,
               "unit": "s", "quality_ok": True,
               "hist_pass_label": "grow/frontier[fused_hist_k8]",
               "hist_pass_mean_s": 0.41 + 0.002 * i} for i in range(4)]

    def fkverdict(newest):
        failures, _ = evaluate(hhist + fkhist + [newest])
        return bool(failures)

    checks += [
        ("fused-K labeled record steady passes", not fkverdict(
            {"config": "goss_regression+fusedk8_force", "value": 30.2,
             "unit": "s", "quality_ok": True,
             "hist_pass_label": "grow/frontier[fused_hist_k8]",
             "hist_pass_mean_s": 0.413})),
        ("fused-K hist pass regression fails", fkverdict(
            {"config": "goss_regression+fusedk8_force", "value": 30.2,
             "unit": "s", "quality_ok": True,
             "hist_pass_label": "grow/frontier[fused_hist_k8]",
             "hist_pass_mean_s": 0.60})),
        ("tagged cell never reads the untagged baseline", not evaluate(
            hhist + fkhist
            + [{"config": "goss_regression", "value": 200.0, "unit": "s",
                "quality_ok": True,
                "hist_pass_label": "grow/frontier[fused_hist_k8]",
                "hist_pass_mean_s": 5.0}])[0]),
    ]
    shist = [{"config": "serve-s-b16-d0", "qps": 1000.0 - 5 * i,
              "p50_s": 0.001, "p99_s": 0.004 + 0.0001 * i,
              "quality_ok": True} for i in range(4)]

    def sverdict(newest):
        failures, _ = evaluate(shist + [newest])
        return bool(failures)

    checks += [
        ("serve record w/o training fields passes", not sverdict(
            {"config": "serve-s-b16-d0", "qps": 990.0, "p50_s": 0.001,
             "p99_s": 0.0041, "quality_ok": True})),
        ("serve p99 regression fails", sverdict(
            {"config": "serve-s-b16-d0", "qps": 990.0, "p50_s": 0.001,
             "p99_s": 0.009, "quality_ok": True})),
        ("serve first record passes", not evaluate(
            [{"config": "serve-new", "qps": 5.0, "p99_s": 0.1}])[0]),
    ]
    # open-loop loadgen records (tools/loadgen.py): same p99 gate, but
    # the record shape carries rows_per_batch instead of bucket fields
    lhist = [{"config": "loadgen-small-r300-d5", "qps": 295.0 + i,
              "rows_per_batch": 6.0 + 0.1 * i, "p50_s": 0.004,
              "p99_s": 0.012 + 0.0002 * i, "quality_ok": True}
             for i in range(4)]

    def lverdict(newest):
        failures, _ = evaluate(lhist + [newest])
        return bool(failures)

    checks += [
        ("open-loop steady p99 passes", not lverdict(
            {"config": "loadgen-small-r300-d5", "qps": 297.0,
             "rows_per_batch": 6.2, "p50_s": 0.004, "p99_s": 0.0125,
             "quality_ok": True})),
        ("open-loop p99 regression fails", lverdict(
            {"config": "loadgen-small-r300-d5", "qps": 297.0,
             "rows_per_batch": 6.2, "p50_s": 0.004, "p99_s": 0.020,
             "quality_ok": True})),
        ("open-loop quality flip fails", lverdict(
            {"config": "loadgen-small-r300-d5", "qps": 297.0,
             "rows_per_batch": 6.2, "p50_s": 0.004, "p99_s": 0.0125,
             "quality_ok": False})),
        ("open-loop first record passes", not evaluate(
            [{"config": "loadgen-new-r50-d0", "qps": 49.0,
              "p99_s": 0.01}])[0]),
    ]
    # multi-tenant scheduler records (tools/submit_jobs.py workloads):
    # sched-only fields (fairness_index, queue_wait, cache hits) ride
    # along without tripping the field-specific gates; the wall gate
    # still judges the workload's end-to-end time, and quality_ok
    # carries the fairness-threshold verdict
    schist = [{"config": "sched-fair-3job", "value": 6.0 + 0.05 * i,
               "unit": "s", "quality_ok": True,
               "fairness_index": 0.95 - 0.001 * i,
               "queue_wait_s": 0.4, "cross_job_cache_hits": 2}
              for i in range(4)]

    def scverdict(newest):
        failures, _ = evaluate(schist + [newest])
        return bool(failures)

    checks += [
        ("sched steady wall passes", not scverdict(
            {"config": "sched-fair-3job", "value": 6.1, "unit": "s",
             "quality_ok": True, "fairness_index": 0.95,
             "queue_wait_s": 0.41, "cross_job_cache_hits": 2})),
        ("sched wall regression fails", scverdict(
            {"config": "sched-fair-3job", "value": 12.0, "unit": "s",
             "quality_ok": True, "fairness_index": 0.95,
             "queue_wait_s": 0.4, "cross_job_cache_hits": 2})),
        ("sched fairness flip fails", scverdict(
            {"config": "sched-fair-3job", "value": 6.1, "unit": "s",
             "quality_ok": False, "fairness_index": 0.45,
             "queue_wait_s": 0.4, "cross_job_cache_hits": 0})),
        ("sched first record passes", not evaluate(
            [{"config": "sched-rr-2job", "value": 3.0, "unit": "s",
              "fairness_index": 0.99}])[0]),
    ]
    # drift-plane records (tools/loadgen.py --shift cells): drift_ok is
    # a quality-style flip gate; psi_max ratio-gates only above the
    # absolute noise floor so small-sample jitter never flaps it
    dhist = [{"config": "loadgen-shift-control", "qps": 200.0,
              "p99_s": 0.010, "quality_ok": True, "drift_ok": True,
              "psi_max": 0.040 + 0.002 * i} for i in range(4)]

    def dverdict(newest):
        failures, _ = evaluate(dhist + [newest])
        return bool(failures)

    checks += [
        ("steady drift record passes", not dverdict(
            {"config": "loadgen-shift-control", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True, "drift_ok": True,
             "psi_max": 0.045})),
        ("drift_ok flip fails", dverdict(
            {"config": "loadgen-shift-control", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True, "drift_ok": False,
             "psi_max": 0.045})),
        ("psi_max below noise floor never ratio-gates", not dverdict(
            {"config": "loadgen-shift-control", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True, "drift_ok": True,
             "psi_max": 0.09})),
        ("psi_max regression over floor fails", dverdict(
            {"config": "loadgen-shift-control", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True, "drift_ok": True,
             "psi_max": 0.40})),
        ("drift-field-free record passes drift gate", not dverdict(
            {"config": "loadgen-shift-control", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True})),
        ("drift first record passes", not evaluate(
            [{"config": "loadgen-shift-new", "drift_ok": True,
              "psi_max": 1.2}])[0]),
    ]
    # hot-swap cells (tools/loadgen.py --swap): swap_pause_p99_s gates
    # like a latency, shed_rate gates on ratio AND on appearing where
    # the trailing history shed nothing
    whist = [{"config": "loadgen-swap-smoke", "qps": 200.0,
              "p99_s": 0.010, "quality_ok": True, "swaps": 3,
              "swap_pause_p99_s": 0.004 + 0.0001 * i, "shed_rate": 0.0}
             for i in range(4)]

    def wverdict(newest):
        failures, _ = evaluate(whist + [newest])
        return bool(failures)

    checks += [
        ("steady swap pause passes", not wverdict(
            {"config": "loadgen-swap-smoke", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True, "swaps": 3,
             "swap_pause_p99_s": 0.0042, "shed_rate": 0.0})),
        ("swap pause regression fails", wverdict(
            {"config": "loadgen-swap-smoke", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True, "swaps": 3,
             "swap_pause_p99_s": 0.02, "shed_rate": 0.0})),
        ("shedding appearing from zero fails", wverdict(
            {"config": "loadgen-swap-smoke", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True, "swaps": 3,
             "swap_pause_p99_s": 0.0042, "shed_rate": 0.05})),
        ("swap quality flip fails", wverdict(
            {"config": "loadgen-swap-smoke", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": False, "swaps": 3,
             "swap_pause_p99_s": 0.0042, "shed_rate": 0.0})),
        ("swap-field-free record passes swap gates", not wverdict(
            {"config": "loadgen-swap-smoke", "qps": 200.0,
             "p99_s": 0.010, "quality_ok": True})),
        ("swap first record passes", not evaluate(
            [{"config": "loadgen-swap-new", "swap_pause_p99_s": 0.5,
              "shed_rate": 0.5}])[0]),
    ]
    shed_hist = [{"config": "loadgen-swap-shed", "quality_ok": True,
                  "swap_pause_p99_s": 0.004, "shed_rate": 0.010}
                 for _ in range(4)]
    checks += [
        ("steady nonzero shed rate passes", not evaluate(
            shed_hist + [{"config": "loadgen-swap-shed",
                          "quality_ok": True,
                          "swap_pause_p99_s": 0.004,
                          "shed_rate": 0.011}])[0]),
        ("shed rate ratio regression fails", bool(evaluate(
            shed_hist + [{"config": "loadgen-swap-shed",
                          "quality_ok": True,
                          "swap_pause_p99_s": 0.004,
                          "shed_rate": 0.10}])[0])),
    ]
    # fleet-summary structural gate (tools/fleet_monitor.py output)
    good_fleet = {
        "schema": FLEET_SUMMARY_SCHEMA,
        "streams": {"rank0.health.jsonl": {
            "stream": "train", "status": "finished", "records": 20,
            "rank": 0, "faults": 0}},
        "per_rank": {"0": {"wait_s": 0.5, "work_s": 1.5,
                           "windows": 2, "wait_fraction": 0.25}},
        "straggler_hist": {"1": 2}, "windows": 2,
        "collective_calls": 9, "faults": {"train": 1},
        "clock_offsets": {}, "complete": True,
    }
    checks += [
        ("well-formed fleet summary passes",
         validate_fleet_summary(good_fleet) == []),
        ("fleet schema mismatch fails",
         bool(validate_fleet_summary(
             dict(good_fleet, schema="lightgbm_tpu.fleet_summary/v0")))),
        ("fleet wait_fraction out of range fails",
         bool(validate_fleet_summary(dict(
             good_fleet,
             per_rank={"0": {"wait_s": 0.5, "work_s": 1.5,
                             "wait_fraction": 1.5}})))),
        ("fleet straggler hist over window count fails",
         bool(validate_fleet_summary(
             dict(good_fleet, straggler_hist={"1": 5})))),
        ("fleet empty streams fails",
         bool(validate_fleet_summary(dict(good_fleet, streams={})))),
        ("fleet missing complete flag fails",
         bool(validate_fleet_summary(
             {k: v for k, v in good_fleet.items()
              if k != "complete"}))),
    ]
    bad = [name for name, ok in checks if not ok]
    for name, ok in checks:
        print(f"bench_gate self-test: {'ok' if ok else 'FAIL'} {name}")
    print(f"bench_gate self-test: {'FAIL' if bad else 'PASS'}")
    return 1 if bad else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fail on wall/HBM/quality regressions in the newest "
                    "BENCH_TRAJECTORY.jsonl records")
    ap.add_argument("--path", default=DEFAULT_PATH)
    ap.add_argument("--window", type=int, default=5,
                    help="trailing records per config forming the "
                         "baseline median (default 5)")
    ap.add_argument("--wall-tol", type=float, default=0.15,
                    help="allowed wall-time regression (default 0.15)")
    ap.add_argument("--hbm-tol", type=float, default=0.20,
                    help="allowed peak-HBM regression (default 0.20)")
    ap.add_argument("--latency-tol", type=float, default=0.20,
                    help="allowed measured dispatch-latency regression "
                         "(default 0.20; only gates device_timing runs)")
    ap.add_argument("--psi-tol", type=float, default=0.50,
                    help="allowed psi_max regression over the trailing "
                         "median (default 0.50; only gates above the "
                         f"{PSI_NOISE_FLOOR:g} PSI noise floor)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in smoke checks and exit")
    ap.add_argument("--fleet-summary", default=None,
                    help="validate a tools/fleet_monitor.py "
                         "fleet_summary.json instead of the "
                         "trajectory")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.fleet_summary:
        return gate_fleet_summary(args.fleet_summary)
    return gate(args.path, args.window, args.wall_tol, args.hbm_tol,
                args.latency_tol, args.psi_tol)


if __name__ == "__main__":
    sys.exit(main())
