"""One fleet view over every health-stream kind (metrics v6/v7 planes).

run_monitor / serve_monitor / sched_monitor each tail ONE stream kind.
This tool tails a directory holding ALL of them at once — the per-rank
training streams of a multi-host run, a serve session's stream, a
scheduler's stream — and folds them into one time-ordered view:

  * one status line per stream (classified by the ``stream`` field the
    v6 writers stamp into their start meta: train / serve / sched; the
    start-record kind is the fallback for v5 streams);
  * the v6 ``dist_window`` records' collective wait-vs-work split: per
    rank, how much of its collective wall was idle waiting for the
    slowest rank (skew-corrected), and WHICH rank was the straggler in
    each window;
  * stall/straggler/fault rollups across every subsystem, with the
    pace-relative staleness detector (tools/streamtail.py) flagging any
    stream that has gone quiet mid-run, and the v7 ``serve_drift``
    records' model-drift verdicts (a drifted resident model renders
    the loud ``!! DRIFT`` banner next to STALL/STALE);
  * a merged tail of the newest records across all streams, ordered by
    the monotonic ``mono_ts`` stamps (corrected by the ``dist_clock``
    offsets when present) — never by wall clocks.

``--summary-out`` additionally writes a machine-readable
``fleet_summary.json`` (schema ``lightgbm_tpu.fleet_summary/v1``):
per-rank wait fraction, slowest-rank histogram, per-subsystem fault
counts — the shape ``bench_gate.py --fleet-summary`` gates.

``--smoke`` is the self-contained CI leg: it launches a real 2-rank
localhost CPU fleet (tools/launch_multihost.py), waits it out, merges
the per-rank traces with tools/fleet_trace.py, renders the fleet view,
writes the summary and validates it with bench_gate — exercising the
whole v6 observability plane in one command.

Usage:
  python tools/fleet_monitor.py obsdir/
  python tools/fleet_monitor.py obsdir/ --follow --timeout 300
  python tools/fleet_monitor.py obsdir/ --summary-out fleet_summary.json
  python tools/fleet_monitor.py --smoke
"""

import argparse
import json
import os
import sys
import time
from collections import deque

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import streamtail  # noqa: E402  (shared tail loop + staleness)

FLEET_SUMMARY_SCHEMA = "lightgbm_tpu.fleet_summary/v1"

# start-record kind -> subsystem, for v5 streams without the meta field
_START_KINDS = {"start": "train", "serve_start": "serve",
                "sched_start": "sched"}
_SUMMARY_KINDS = ("summary", "serve_summary", "sched_summary")
# cap on retained dist_window records per stream: totals keep folding,
# only the raw records rotate
_WINDOW_KEEP = 64


class FleetStream(streamtail.JsonlFolder):
    """Subsystem-agnostic fold of ONE health stream: classification,
    progress, faults, and the v6 dist records."""

    def __init__(self):
        super().__init__()
        self.stream = None              # train / serve / sched / ?
        self.meta = None
        self.rank = None
        self.world = None
        self.last_iter = None
        self.faults = 0
        self.recent = deque(maxlen=64)  # (mono_ts, kind, detail)
        self.dist_windows = deque(maxlen=_WINDOW_KEEP)
        self.wait_s = 0.0               # this stream's own rank totals
        self.work_s = 0.0
        self.clock = None               # newest dist_clock offset table
        self.drifts = {}                # model_id -> newest serve_drift

    def on_record(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind in _START_KINDS or kind == "resume":
            self.meta = rec
            self.stream = (rec.get("stream")
                           or _START_KINDS.get(kind, self.stream))
            if rec.get("rank") is not None:
                self.rank = rec.get("rank")
                self.world = rec.get("world")
        detail = rec.get("iter")
        if detail is None:
            detail = rec.get("job") or rec.get("event")
        self.recent.append((rec.get("mono_ts"), kind, detail))
        if kind == "iter":
            self.last_iter = rec.get("iter")
        elif kind in ("fault", "serve_fault"):
            self.faults += 1
        elif kind == "dist_window":
            self.dist_windows.append(rec)
            self.wait_s += float(rec.get("wait_s") or 0.0)
            self.work_s += float(rec.get("work_s") or 0.0)
            if rec.get("rank") is not None:
                self.rank = rec.get("rank")
        elif kind == "dist_clock":
            self.clock = rec.get("offsets")
        elif kind == "serve_drift":
            self.drifts[rec.get("model", "?")] = rec
        elif kind in _SUMMARY_KINDS:
            self.summary = rec

    @property
    def status(self):
        if self.summary is not None:
            return ("aborted" if self.summary.get("aborted")
                    else "finished")
        return "running" if self.records else "empty"

    def label(self):
        parts = [self.stream or "?"]
        if self.rank is not None:
            parts.append(f"rank{self.rank}" +
                         (f"/{self.world}" if self.world else ""))
        return ":".join(parts)


def load_dir(dirpath):
    """{path: FleetStream} over every *.jsonl stream under a dir."""
    states = {}
    for name in sorted(os.listdir(dirpath)):
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(dirpath, name)
        try:
            streamtail.read_stream(path, states.setdefault(
                path, FleetStream()))
        except OSError:
            states.pop(path, None)
    return states


def _clock_table(states):
    """The fleet's clock-offset table (any stream carries the whole
    allgathered table; the newest record wins within each stream)."""
    for state in states.values():
        if state.clock:
            return {int(r): v for r, v in state.clock.items()}
    return {}


def build_summary(states):
    """The machine-readable rollup bench_gate.py gates."""
    offsets = _clock_table(states)
    per_rank = {}
    straggler_by_seq = {}
    calls = 0
    for state in states.values():
        for rec in state.dist_windows:
            r = rec.get("rank")
            if r is None:
                continue
            # each rank's stream carries its OWN wait/work split; the
            # shared fields (straggler, per-window calls) are folded
            # once per window via the seq key, not once per stream
            slot = per_rank.setdefault(str(r), {"wait_s": 0.0,
                                                "work_s": 0.0,
                                                "windows": 0})
            slot["wait_s"] += float(rec.get("wait_s") or 0.0)
            slot["work_s"] += float(rec.get("work_s") or 0.0)
            slot["windows"] += 1
            seq = rec.get("seq")
            if seq is not None and seq not in straggler_by_seq:
                straggler_by_seq[seq] = rec.get("straggler")
                calls += int(rec.get("calls") or 0)
    straggler_hist = {}
    for straggler in straggler_by_seq.values():
        if straggler is not None:
            key = str(straggler)
            straggler_hist[key] = straggler_hist.get(key, 0) + 1
    for slot in per_rank.values():
        wall = slot["wait_s"] + slot["work_s"]
        slot["wait_s"] = round(slot["wait_s"], 6)
        slot["work_s"] = round(slot["work_s"], 6)
        slot["wait_fraction"] = round(slot["wait_s"] / wall, 6) \
            if wall > 0 else 0.0
    faults = {}
    streams = {}
    for path, state in states.items():
        sub = state.stream or "?"
        if state.faults:
            faults[sub] = faults.get(sub, 0) + state.faults
        streams[os.path.basename(path)] = {
            "stream": sub, "status": state.status,
            "records": state.records, "rank": state.rank,
            "faults": state.faults,
        }
    return {
        "schema": FLEET_SUMMARY_SCHEMA,
        "streams": streams,
        "per_rank": per_rank,
        "straggler_hist": straggler_hist,
        "windows": len(straggler_by_seq),
        "collective_calls": calls,
        "faults": faults,
        "clock_offsets": {str(r): v for r, v in sorted(offsets.items())},
        "complete": bool(states) and all(
            s.summary is not None for s in states.values()),
    }


def render(states, dirpath, tail=14):
    """The one fleet plane: per-stream lines, wait/work rollup,
    stall/straggler flags, merged mono-ordered tail."""
    lines = [f"fleet {dirpath}: {len(states)} stream(s)"]
    if not states:
        lines.append("  no *.jsonl streams found")
        return "\n".join(lines)
    offsets = _clock_table(states)

    def corrected(mono, rank):
        if not isinstance(mono, (int, float)):
            return None
        entry = offsets.get(rank) if rank is not None else None
        return mono + float(entry["offset_s"]) if entry else mono

    merged = []
    for path, state in sorted(states.items(),
                              key=lambda kv: kv[1].label()):
        line = f"  {state.label()}: [{state.status}] " \
               f"{state.records} records"
        if state.last_iter is not None:
            line += f", iter {state.last_iter}"
        if state.wait_s or state.work_s:
            line += (f", collectives wait {state.wait_s:.3f}s / "
                     f"work {state.work_s:.3f}s")
        if state.faults:
            line += f", {state.faults} fault(s)"
        lines.append(line)
        for mono, kind, detail in state.recent:
            merged.append((corrected(mono, state.rank) or 0.0,
                           state.label(), kind, detail))

    summary = build_summary(states)
    hist = summary["straggler_hist"]
    if hist:
        worst = max(hist, key=hist.get)
        lines.append(
            f"  straggler: rank{worst} slowest in {hist[worst]} of "
            f"{summary['windows']} window(s) "
            + " ".join(f"rank{r}={n}" for r, n in sorted(hist.items())))
    for rank, slot in sorted(summary["per_rank"].items()):
        if slot["wait_fraction"] >= 0.5:
            lines.append(
                f"  !! WAIT-BOUND rank{rank}: {slot['wait_fraction']:.0%}"
                f" of its collective wall spent waiting for slower "
                f"ranks")
    for path, state in sorted(states.items(),
                              key=lambda kv: kv[1].label()):
        for mid, d in sorted(state.drifts.items()):
            if d.get("drifted"):
                lines.append(
                    f"  !! DRIFT {state.label()}: model {mid} "
                    f"psi_max={d.get('psi_max', 0):.3f} at/over "
                    f"threshold {d.get('threshold', '?')} "
                    f"({d.get('rows', '?')} rows) — refit trigger armed")
    for path, state in states.items():
        hit = streamtail.stream_stale(state,
                                      streamtail.stream_age_s(path))
        if hit is not None:
            lines.append(
                f"  !! STALE {state.label()}: no new record for "
                f"{hit[0]:.1f}s, over {streamtail.STALL_GAP_FACTOR:g}x "
                f"its median inter-record gap {hit[1]:.2f}s")
    merged.sort(key=lambda r: r[0])
    if merged:
        lines.append(f"  tail ({min(tail, len(merged))} newest, "
                     f"mono-ordered):")
        for mono, label, kind, detail in merged[-tail:]:
            at = f"@{detail}" if detail is not None else ""
            lines.append(f"    [{mono:12.3f}] {label} {kind}{at}")
    return "\n".join(lines)


def write_summary(states, out_path):
    summary = build_summary(states)
    with open(out_path, "w") as fh:
        json.dump(summary, fh, indent=1, sort_keys=True)
    return summary


def follow(dirpath, interval, timeout, out=sys.stdout,
           summary_out=None):
    """Re-render until every stream has its terminal record (exit 0);
    2 when the directory never yields a stream, 3 on timeout."""
    deadline = time.monotonic() + timeout if timeout > 0 else None
    while True:
        states = load_dir(dirpath) if os.path.isdir(dirpath) else {}
        if states:
            out.write(render(states, dirpath) + "\n")
            out.flush()
            if all(s.summary is not None for s in states.values()):
                if summary_out:
                    write_summary(states, summary_out)
                return 0
        if deadline is not None and time.monotonic() >= deadline:
            if not states:
                out.write(f"fleet_monitor: no streams under "
                          f"{dirpath}\n")
                return 2
            if summary_out:
                write_summary(states, summary_out)
            out.write("fleet_monitor: timeout waiting for every "
                      "stream's terminal record\n")
            return 3
        time.sleep(interval)


# ------------------------------------------------------------------ smoke
def _write_csv(path, seed, n=240):
    """Deterministic toy regression CSV (no numpy dependency here —
    the fleet children load it with the normal data path)."""
    import random
    r = random.Random(seed)
    with open(path, "w") as fh:
        for _ in range(n):
            x = [r.random() for _ in range(4)]
            y = 2.0 * x[0] + x[1] + 0.1 * r.random()
            fh.write(",".join(f"{v:.6f}" for v in [y] + x) + "\n")


def smoke(workdir=None, hosts=2, out=sys.stdout):
    """End-to-end CI leg: real 2-rank CPU fleet -> merged trace ->
    fleet view -> validated fleet_summary.json.  Returns 0 on PASS."""
    import shutil
    import tempfile
    import bench_gate
    import fleet_trace
    from launch_multihost import launch

    keep = workdir is not None
    base = os.path.abspath(workdir or tempfile.mkdtemp(
        prefix="lgbm_fleet_smoke_"))
    obs = os.path.join(base, "obs")
    os.makedirs(obs, exist_ok=True)
    try:
        argvs, cwds, extra_env, logs = [], [], [], []
        for r in range(hosts):
            d = os.path.join(base, f"r{r}")
            os.makedirs(d, exist_ok=True)
            _write_csv(os.path.join(d, "train.csv"), seed=1234)
            argvs.append([
                sys.executable, "-m", "lightgbm_tpu", "task=train",
                "data=train.csv", "label_column=0",
                "objective=regression", "num_iterations=8",
                "num_leaves=7", "min_data_in_leaf=5", "verbosity=1",
                "tpu_boost_chunk=1", "seed=7", "snapshot_freq=2",
                "collective_timeout_s=60", "telemetry_level=2",
                "fleet_obs_sync_iters=3", "output_model=model.txt",
                f"health_out={obs}/rank{{rank}}.health.jsonl"])
            cwds.append(d)
            extra_env.append({"LIGHTGBM_TPU_TRACE_JSON":
                              os.path.join(obs,
                                           f"rank{r}.trace.json")})
            logs.append(open(os.path.join(d, "run.log"), "w"))
        try:
            run = launch(argvs, cwds=cwds, extra_env=extra_env,
                         stdouts=logs)
            codes = run.wait(timeout_s=240.0)
        finally:
            for fh in logs:
                fh.close()
        checks = [("all ranks exited 0 " + str(codes),
                   codes == [0] * hosts)]

        merged_path = os.path.join(obs, "smoke.fleet.json")
        rc = fleet_trace.main([obs, "-o", merged_path])
        checks.append(("fleet_trace merged the per-rank traces",
                       rc == 0 and os.path.exists(merged_path)))
        if os.path.exists(merged_path):
            with open(merged_path) as fh:
                merged = json.load(fh)
            pids = {ev.get("pid") for ev in merged["traceEvents"]
                    if ev.get("ph") == "X"}
            checks.append(
                (f"merged trace has one lane per rank {sorted(pids)}",
                 pids == set(range(hosts))))

        states = load_dir(obs)
        out.write(render(states, obs) + "\n")
        summary_path = os.path.join(obs, "fleet_summary.json")
        summary = write_summary(states, summary_path)
        checks.append(("every stream reached its terminal record",
                       summary["complete"]))
        checks.append((f"windows attributed ({summary['windows']})",
                       summary["windows"] >= 1))
        errors = bench_gate.validate_fleet_summary(summary)
        checks.append(("bench_gate accepts fleet_summary.json "
                       + "; ".join(errors), not errors))

        bad = [name for name, ok in checks if not ok]
        for name, ok in checks:
            out.write(f"fleet_monitor smoke: {'ok' if ok else 'FAIL'} "
                      f"{name}\n")
        out.write(f"fleet_monitor smoke: "
                  f"{'FAIL' if bad else 'PASS'} ({base})\n")
        if bad:
            for r in range(hosts):
                log = os.path.join(base, f"r{r}", "run.log")
                if os.path.exists(log):
                    with open(log) as fh:
                        tail = fh.read()[-2000:]
                    out.write(f"--- rank {r} log tail ---\n{tail}\n")
        return 1 if bad else 0
    finally:
        if not keep:
            shutil.rmtree(base, ignore_errors=True)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge every health-stream kind in a directory "
                    "into one fleet view (train/serve/sched/dist)")
    ap.add_argument("path", nargs="?",
                    help="directory of health JSONL streams")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing until every stream's terminal "
                         "record lands")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--timeout", type=float, default=0.0,
                    help="--follow gives up after this many seconds "
                         "(0 = wait forever)")
    ap.add_argument("--summary-out", default=None,
                    help="also write the machine-readable "
                         "fleet_summary.json here")
    ap.add_argument("--tail", type=int, default=14,
                    help="merged-tail length (default 14)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the self-contained 2-rank CPU fleet "
                         "smoke (ignores PATH unless given as the "
                         "work dir to keep)")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke(workdir=args.path)
    if not args.path:
        ap.error("PATH is required unless --smoke")
    if args.follow:
        return follow(args.path, max(0.05, args.interval),
                      args.timeout, summary_out=args.summary_out)
    if not os.path.isdir(args.path):
        print(f"fleet_monitor: not a directory: {args.path}")
        return 2
    states = load_dir(args.path)
    print(render(states, args.path, tail=args.tail))
    if args.summary_out:
        write_summary(states, args.summary_out)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(0)
