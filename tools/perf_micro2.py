"""Device-time microbench with in-jit repetition: each primitive runs K
times inside ONE jit with a data dependency, so (t(K) - t(1)) / (K - 1) is
pure device compute, immune to dispatch/RPC overhead of the tunneled
backend (tools/perf_micro.py measured dispatch, not compute)."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_500_000
F = 28
B = 64
K = 9


def main():
    import jax
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache()
    import jax.numpy as jnp
    from jax import lax
    from lightgbm_tpu.ops.pallas_histogram import (
        histogram_segment, pack_channels, pick_block_rows)
    from lightgbm_tpu.models.grower_seg import (
        _pack_bins_words, _pack_w8_words, _unpack_bins_words,
        _unpack_w8_words)
    from lightgbm_tpu.ops.split import FeatureMeta, SplitParams, best_split

    rb = pick_block_rows(F, B, N)
    npad = -(-N // rb) * rb
    nblk = npad // rb
    print(f"N={N} rb={rb} blocks={nblk} backend={jax.default_backend()}",
          flush=True)
    rng = np.random.RandomState(0)
    F4 = F + (-F) % 4
    binsT = jnp.asarray(rng.randint(0, B, size=(F4, npad),
                                    dtype=np.int64).astype(np.uint8))
    grad = jnp.asarray(rng.normal(size=npad).astype(np.float32))
    w8 = pack_channels(grad, jnp.ones(npad, jnp.float32),
                       jnp.ones(npad, jnp.float32))
    leaf_id = jnp.asarray(rng.randint(0, 2, size=npad).astype(np.int32))

    def timed(make_fn, label, scale=1.0):
        f1 = jax.jit(make_fn(1))
        fK = jax.jit(make_fn(K))
        r = np.asarray(f1(binsT, w8, leaf_id)).sum()  # compile+run
        r = np.asarray(fK(binsT, w8, leaf_id)).sum()
        ts = []
        for f in (f1, fK):
            t0 = time.perf_counter()
            np.asarray(f(binsT, w8, leaf_id)).sum()
            ts.append(time.perf_counter() - t0)
        per = (ts[1] - ts[0]) / (K - 1)
        print(f"{label}: {per*1e3:.2f} ms/op  (t1={ts[0]*1e3:.1f} "
              f"tK={ts[1]*1e3:.1f}) {scale_note(per, scale)}", flush=True)
        return per

    def scale_note(per, per_tree_calls):
        return f"-> x{per_tree_calls:.0f}/tree = " \
               f"{per * per_tree_calls * 1e3:.0f} ms"

    # (a) full-N segment histogram, K reps with alternating target leaf
    def mk_hist(reps):
        def fn(bT, w, lid):
            def body(i, acc):
                h = histogram_segment(bT, w, lid, jnp.int32(0),
                                      jnp.int32(nblk), i % 2, B, rb)
                return acc + h
            return lax.fori_loop(0, reps, body,
                                 jnp.zeros((F4, B, 8), jnp.float32))
        return fn
    # sum of smaller-child intervals per tree ~ 10N with default compaction
    timed(mk_hist, "hist_full_N", scale=10.0)

    # (b) compaction sort
    def mk_sort(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                ops = ((lid_c + i,) + tuple(_pack_bins_words(bT))
                       + tuple(_pack_w8_words(w)))
                out = lax.sort(ops, num_keys=1, is_stable=True)
                return out[0]
            return lax.fori_loop(0, reps, body, lid)
        return fn
    timed(mk_sort, "compact_sort", scale=4.0)

    # (c) routing pass
    def mk_route(reps):
        def fn(bT, w, lid):
            def body(i, lid_c):
                fcol = lax.dynamic_slice_in_dim(bT, i % F, 1, axis=0)[0, :]
                go_left = fcol.astype(jnp.int32) <= 31
                in_leaf = lid_c == i % 7
                return jnp.where(in_leaf & ~go_left, i % 7 + 1, lid_c)
            return lax.fori_loop(0, reps, body, lid)
        return fn
    timed(mk_route, "route_pass", scale=254.0)

    # (d) per-leaf best-split scan
    fmeta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        is_cat=jnp.zeros(F, bool),
        monotone=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    sp = SplitParams(has_cat=False)

    def mk_scan(reps):
        def fn(bT, w, lid):
            h0 = histogram_segment(bT, w, lid, jnp.int32(0), jnp.int32(1),
                                   jnp.int32(0), B, rb)
            hist = jnp.stack([h0[..., 0] + h0[..., 1],
                              h0[..., 2] + h0[..., 3],
                              h0[..., 4]], axis=-1)[:F]

            def body(i, acc):
                info = best_split(hist + acc * 1e-9, 1.0, float(N),
                                  float(N), fmeta, sp,
                                  jnp.ones(F, jnp.float32))
                return acc + info.gain
            return lax.fori_loop(0, reps, body, jnp.float32(0.0))
        return fn
    timed(mk_scan, "scan_one", scale=508.0)


main()
