"""Live/post-hoc terminal summary of a run-health JSONL stream.

The stream is the append-only file a training run writes for
``health_out=`` / ``LIGHTGBM_TPU_HEALTH_JSONL`` (see
lightgbm_tpu/utils/telemetry.py, schema ``lightgbm_tpu.health/v1``):
``start``/``resume``, per-iteration ``iter`` records (chunk size, tree
shape, grad/hess stats, HBM), ``eval`` metric records, ``snapshot`` and
``fault`` events, and a closing ``summary``.

One-shot mode renders the stream as it stands — running OR finished.
``--follow`` tails the file (byte-offset incremental reads, so a
multi-hour stream is not re-parsed every tick), re-rendering every
``--interval`` seconds until the ``summary`` record lands (exit 0) or
``--timeout`` seconds pass without one (exit 3).

``--fleet dir/`` merges every ``*.jsonl`` stream in a directory — the
per-rank files of a multi-host run (cli.py stamps ``rank``/``world``
into each stream's start meta) — into one view: per-rank progress and
pace, an interleaved tail of the newest records across ranks, and a
LOUD stall flag when one rank's last iteration lags the fleet median
(the signature of a wedged collective: the stuck rank stops appending
while the others time out at the barrier behind it).  A second,
pace-relative detector flags any unfinished stream whose file has no
new line within 2x its own median inter-record gap — this catches a
wedge the lag check can't (every rank stuck at the same iteration)
and is reused by ``tools/sched_monitor.py`` for per-job streams.

The tail loop and the staleness detectors live in
``tools/streamtail.py`` (shared with serve_monitor / sched_monitor /
fleet_monitor); this module re-exports them under their historical
names.  For a fleet view that merges serve and scheduler streams too
(plus the v6 dist/straggler records), see ``tools/fleet_monitor.py`` —
``--fleet`` here remains the train-only per-rank view.

Usage:
  python tools/run_monitor.py run.health.jsonl
  python tools/run_monitor.py run.health.jsonl --follow --interval 2
  python tools/run_monitor.py --fleet rundir/ [--follow]
"""

import argparse
import os
import sys
import time
from collections import deque

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import streamtail  # noqa: E402  (shared tail loop + staleness)
from streamtail import (  # noqa: E402,F401  (re-exported API)
    STALL_LAG_ITERS, STALL_GAP_FACTOR, STALE_MIN_RECORDS,
    median_record_gap, stream_stale)

# re-export under the historical name (sched_monitor/tests import it)
_stream_age_s = streamtail.stream_age_s


class StreamState(streamtail.JsonlFolder):
    """Folded view of a health stream; feed() (streamtail.JsonlFolder)
    accepts raw JSONL bytes incrementally and tolerates a torn trailing
    line (kept in the tail buffer until its newline arrives)."""

    def __init__(self):
        super().__init__()
        self.start = None
        self.resumes = []
        self.iters = {}                 # iter -> last record wins
        self.evals = {}                 # iter -> last record wins
        self.snapshots = []
        self.faults = []
        self.recent = deque(maxlen=64)  # (t, kind, iter) tail for --fleet

    def on_record(self, rec: dict) -> None:
        kind = rec.get("kind")
        self.recent.append((rec.get("t"), kind, rec.get("iter")))
        if kind == "start":
            self.start = rec
        elif kind == "resume":
            self.resumes.append(rec)
        elif kind == "iter":
            self.iters[int(rec.get("iter", -1))] = rec
        elif kind == "eval":
            self.evals[int(rec.get("iter", -1))] = rec
        elif kind == "snapshot":
            self.snapshots.append(rec)
        elif kind == "fault":
            self.faults.append(rec)
        elif kind == "summary":
            self.summary = rec


def _fmt_bytes(n):
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


def _dispatch_rate(state: StreamState):
    """EWMA boosting rate (iterations/sec) from the per-chunk measured
    ``dispatch_wall_s`` fields (v4 streams with device timing/chunking;
    None on older streams — the caller falls back to stream-window
    timestamps)."""
    ewma = None
    for it in sorted(state.iters):
        rec = state.iters[it]
        wall = rec.get("dispatch_wall_s")
        chunk = rec.get("chunk") or 1
        if not isinstance(wall, (int, float)) or wall <= 0:
            continue
        rate = float(chunk) / float(wall)
        ewma = rate if ewma is None else 0.7 * ewma + 0.3 * rate
    return ewma


def _fmt_eta(seconds: float) -> str:
    seconds = int(round(seconds))
    if seconds >= 3600:
        return f"{seconds // 3600}h{(seconds % 3600) // 60:02d}m"
    if seconds >= 60:
        return f"{seconds // 60}m{seconds % 60:02d}s"
    return f"{seconds}s"


def render(state: StreamState, path: str) -> str:
    lines = []
    if state.summary is not None:
        status = "aborted" if state.summary.get("aborted") else "finished"
    elif state.start is not None or state.iters:
        status = "running"
    else:
        status = "empty"
    schema = (state.start or {}).get("schema", "?")
    lines.append(f"run-health {os.path.basename(path)} [{status}] "
                 f"schema={schema} records={state.records}")

    total = (state.start or {}).get("num_iterations")
    if state.iters:
        done = max(state.iters) + 1
        first, last = min(state.iters), max(state.iters)
        progress = f"progress: {done}"
        if total:
            progress += f"/{int(total)} ({100.0 * done / total:.0f}%)"
        progress += " iterations"
        t0 = state.iters[first].get("t")
        t1 = state.iters[last].get("t")
        if (t0 is not None and t1 is not None and last > first
                and t1 > t0):
            rate = (last - first) / (t1 - t0)
            progress += f", {rate:.2f} it/s in the stream window"
        chunk = state.iters[last].get("chunk")
        if chunk:
            progress += f", chunk={chunk}"
        # memory tier of the bin matrix (v4 streams; older streams have
        # no data_tier field and render unchanged)
        tier = state.iters[last].get("data_tier")
        if tier:
            progress += f", tier={tier}"
        lines.append("  " + progress)
        ewma = _dispatch_rate(state)
        if ewma is not None and ewma > 0:
            pace = (f"  dispatch pace: {ewma:.2f} it/s "
                    "(EWMA of measured chunk walls)")
            if total and state.summary is None and done < total:
                pace += f", ETA {_fmt_eta((int(total) - done) / ewma)}"
            lines.append(pace)
        rec = state.iters[last]
        trees = rec.get("trees") or []
        if trees:
            leaves = [t.get("leaves", 0) for t in trees]
            depth = max(t.get("depth", 0) for t in trees)
            gain = sum(t.get("gain_sum", 0.0) for t in trees)
            lines.append(f"  trees@{last}: {len(trees)} tree(s), "
                         f"leaves={leaves} depth<={depth} "
                         f"gain_sum={gain:g}")
        grad, hess = rec.get("grad"), rec.get("hess")
        if grad:
            nf = sum(grad.get("nonfinite", [])) + \
                sum((hess or {}).get("nonfinite", []))
            lines.append(
                f"  grad@{last}: min={min(grad['min']):g} "
                f"max={max(grad['max']):g} l2={max(grad['l2']):g}"
                + (f"  !! nonfinite={nf}" if nf else ""))
        total_nf = 0
        for r in state.iters.values():
            for sec in ("grad", "hess"):
                total_nf += sum((r.get(sec) or {}).get("nonfinite", []))
        if total_nf:
            lines.append(f"  NONFINITE: {total_nf} values across the "
                         f"run — check learning_rate/objective")
        hbm = rec.get("hbm")
        if hbm:
            lines.append(f"  hbm: {_fmt_bytes(hbm.get('bytes_in_use', 0))}"
                         f" in use, peak "
                         f"{_fmt_bytes(hbm.get('peak_bytes_in_use', 0))}")
    else:
        lines.append("  progress: no iteration records yet")

    if state.evals:
        it = max(state.evals)
        metrics = state.evals[it].get("metrics") or {}
        parts = [f"{k}={v:g}" for k, v in sorted(metrics.items())]
        lines.append(f"  eval@{it}: " + " ".join(parts))
    if state.resumes:
        its = [r.get("iter") for r in state.resumes]
        lines.append(f"  resumed {len(state.resumes)}x (at iteration(s) "
                     f"{its}) — stream is contiguous across kills")
    if state.snapshots:
        lines.append(f"  snapshots: {len(state.snapshots)}, newest at "
                     f"iteration {state.snapshots[-1].get('iter')}")
    if state.faults:
        kinds = {}
        for f in state.faults:
            kinds[f.get("fault", "?")] = kinds.get(f.get("fault", "?"),
                                                   0) + 1
        parts = [f"{k}={v}" for k, v in sorted(kinds.items())]
        lines.append("  faults: " + " ".join(parts))
    if state.summary is not None:
        s = state.summary
        lines.append(f"  summary: {s.get('records', '?')} records, "
                     f"{s.get('iterations', '?')} iterations, "
                     f"aborted={bool(s.get('aborted'))}")
        imp = (s.get("feature_importance") or {}).get("top") or []
        if imp:
            parts = [f"{e.get('feature', '?')}="
                     f"{e.get('gain', 0):g}g/{e.get('split', 0)}s"
                     for e in imp[:6]]
            used = (s.get("feature_importance") or {}).get("features_used")
            lines.append("  importance (gain/splits): " + " ".join(parts)
                         + (f"  ({used} features used)" if used else ""))
    return "\n".join(lines)


def _rank_label(name: str, state: StreamState) -> str:
    """rankR/W from the stream's start meta (multi-host runs stamp
    both); the filename is the fallback for streams without it."""
    meta = state.start or {}
    r, w = meta.get("rank"), meta.get("world")
    if r is not None:
        return f"rank{r}/{w}" if w else f"rank{r}"
    return os.path.basename(name)


def load_fleet(dirpath):
    """{path: StreamState} over every *.jsonl stream in a directory."""
    states = {}
    for name in sorted(os.listdir(dirpath)):
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(dirpath, name)
        state = StreamState()
        try:
            with open(path, "rb") as fh:
                state.feed(fh.read())
        except OSError:
            continue
        states[path] = state
    return states


def _fleet_median_iter(states):
    last = sorted(max(s.iters) for s in states.values() if s.iters)
    if not last:
        return None
    mid = len(last) // 2
    return (last[mid] if len(last) % 2
            else (last[mid - 1] + last[mid]) // 2)


def fleet_stale(states, ages=None):
    """[(label, age_s, median_gap)] for every unfinished stream whose
    file has gone quiet for > STALL_GAP_FACTOR x its median
    inter-record gap.  ``ages`` optionally maps path -> age seconds
    (tests); the default reads file mtimes."""
    out = []
    for path, state in states.items():
        age = (ages.get(path) if ages is not None
               else _stream_age_s(path))
        hit = stream_stale(state, age)
        if hit is not None:
            out.append((_rank_label(path, state), hit[0], hit[1]))
    return out


def fleet_stalled(states):
    """[(label, last_iter, median)] for every unfinished rank whose
    newest iteration lags the fleet median by >= STALL_LAG_ITERS."""
    median = _fleet_median_iter(states)
    if median is None:
        return []
    out = []
    for path, state in states.items():
        if state.summary is not None:
            continue
        last = max(state.iters) if state.iters else -1
        if median - last >= STALL_LAG_ITERS:
            out.append((_rank_label(path, state), last, median))
    return out


def render_fleet(states, dirpath, tail=12):
    """The merged view: one pace line per rank, the interleaved tail of
    the newest records across every stream, and the stall flags."""
    lines = [f"fleet {dirpath}: {len(states)} stream(s)"]
    if not states:
        lines.append("  no *.jsonl streams found")
        return "\n".join(lines)
    merged = []
    for path, state in states.items():
        label = _rank_label(path, state)
        if state.summary is not None:
            status = ("aborted" if state.summary.get("aborted")
                      else "finished")
        elif state.iters or state.start:
            status = "running"
        else:
            status = "empty"
        line = f"  {label}: [{status}] {state.records} records"
        if state.iters:
            first, last = min(state.iters), max(state.iters)
            line += f", iter {last}"
            t0 = state.iters[first].get("t")
            t1 = state.iters[last].get("t")
            if (t0 is not None and t1 is not None and last > first
                    and t1 > t0):
                line += f", {(last - first) / (t1 - t0):.2f} it/s"
        if state.faults:
            line += f", {len(state.faults)} fault(s)"
        lines.append(line)
        for t, kind, it in state.recent:
            merged.append((t if t is not None else 0.0, label, kind, it))
    stalls = fleet_stalled(states)
    for label, last, median in stalls:
        lines.append(
            f"  !! STALL {label}: last iteration {last} lags the fleet "
            f"median {median} by {median - last} — rank wedged or its "
            f"stream stopped (others will hit the collective timeout)")
    for label, age, gap in fleet_stale(states):
        lines.append(
            f"  !! STALE {label}: no new record for {age:.1f}s, over "
            f"{STALL_GAP_FACTOR:g}x its median inter-record gap "
            f"{gap:.2f}s — stream has gone quiet mid-run")
    merged.sort(key=lambda r: r[0])
    if merged:
        lines.append(f"  tail ({min(tail, len(merged))} newest across "
                     f"ranks):")
        for t, label, kind, it in merged[-tail:]:
            at = f"@{it}" if it is not None else ""
            lines.append(f"    [{t:9.3f}s] {label} {kind}{at}")
    return "\n".join(lines)


def follow_fleet(dirpath, interval, timeout, out=sys.stdout):
    """Re-render the merged view until every stream has its summary
    (exit 0), stall-flagging laggards along the way; exit 2 when the
    directory never yields a stream, 3 on timeout."""
    deadline = time.monotonic() + timeout if timeout > 0 else None
    while True:
        states = load_fleet(dirpath) if os.path.isdir(dirpath) else {}
        if states:
            out.write(render_fleet(states, dirpath) + "\n")
            out.flush()
            if all(s.summary is not None for s in states.values()):
                return 0
        if deadline is not None and time.monotonic() >= deadline:
            if not states:
                out.write(f"run_monitor: no streams under {dirpath}\n")
                return 2
            out.write("run_monitor: timeout waiting for every rank's "
                      "summary record\n")
            return 3
        time.sleep(interval)


def follow(path, interval, timeout, out=sys.stdout):
    """Tail the stream until its summary record lands.  Returns 0 on a
    completed stream, 2 when the file never appears, 3 on timeout."""
    return streamtail.follow_stream(
        path, StreamState, render, interval, timeout, out,
        name="run_monitor",
        timeout_msg="run_monitor: timeout waiting for the summary "
                    "record (run still alive?)\n")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="summarize a lightgbm_tpu run-health JSONL stream, "
                    "live or post-hoc")
    ap.add_argument("path",
                    help="health JSONL stream, or a directory of "
                         "per-rank streams with --fleet")
    ap.add_argument("--fleet", action="store_true",
                    help="treat PATH as a directory of per-rank "
                         "streams; merge them into one view with "
                         "per-rank pace and stall flags")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing until the summary record lands")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--follow poll period in seconds (default 2)")
    ap.add_argument("--timeout", type=float, default=0.0,
                    help="--follow gives up after this many seconds "
                         "(0 = wait forever)")
    args = ap.parse_args(argv)
    if args.fleet:
        if args.follow:
            return follow_fleet(args.path, max(0.05, args.interval),
                                args.timeout)
        if not os.path.isdir(args.path):
            print(f"run_monitor: --fleet needs a directory: {args.path}")
            return 2
        print(render_fleet(load_fleet(args.path), args.path))
        return 0
    if args.follow:
        return follow(args.path, max(0.05, args.interval), args.timeout)
    if not os.path.exists(args.path):
        print(f"run_monitor: no such stream: {args.path}")
        return 2
    state = StreamState()
    with open(args.path, "rb") as fh:
        state.feed(fh.read())
    print(render(state, args.path))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # e.g. piped into head
        sys.exit(0)
