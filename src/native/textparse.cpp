// Native LibSVM tokenizer.
//
// The reference parses text in C++ (LibSVMParser, src/io/parser.cpp /
// Common::Atof) while the Python path split()s every token in the
// interpreter — the last interpreter-bound leg of text ingestion (dense
// CSV already rides the pandas C tokenizer).  Two passes over the raw
// byte buffer: scan (row count + max feature index) then fill a dense
// row-major matrix whose column 0 is the label and column idx+1 is
// feature idx — exactly the layout lightgbm_tpu.core.parser._parse_libsvm
// produces, which is the spec (results must match it exactly).
//
// Built on demand by lightgbm_tpu/core/native.py with the system g++.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

inline const char* skip_ws(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    return p;
}

inline const char* next_line(const char* p, const char* end) {
    while (p < end && *p != '\n') ++p;
    return p < end ? p + 1 : end;
}

// a line is blank when it holds only whitespace
inline bool blank_line(const char* p, const char* end) {
    for (; p < end && *p != '\n'; ++p) {
        if (*p != ' ' && *p != '\t' && *p != '\r') return false;
    }
    return true;
}

// index token must be an integer (optional sign + digits); non-numeric
// prefixes like "qid" are skipped, matching the Python parser
inline bool all_digits(const char* p, const char* end) {
    if (p < end && (*p == '+' || *p == '-')) ++p;
    if (p >= end) return false;
    for (; p < end; ++p) {
        if (*p < '0' || *p > '9') return false;
    }
    return true;
}

// parse a float token with the Python float() acceptance rules (the
// spec): full consumption, no hex literals (strtod accepts 0x..,
// float() raises).  *ok = false makes the caller fail the whole parse
// over to the Python parser so its error behavior is preserved.
inline double parse_float_checked(const char* p, const char* end,
                                  bool* ok) {
    if (p >= end) {
        *ok = false;
        return 0.0;
    }
    for (const char* q = p; q < end; ++q) {
        if (*q == 'x' || *q == 'X') {
            *ok = false;
            return 0.0;
        }
    }
    char* after = nullptr;
    double v = strtod(p, &after);
    *ok = (after == end);
    return v;
}

}  // namespace

extern "C" {

// Pass 1: rows (non-blank lines) and the max feature index seen.
// Returns 0, or -1 on a negative feature index (the Python parser
// writes those into column 0 — fall back to the spec).  Label/value
// validation happens in the fill pass, which parses them anyway.
int64_t lgbmtpu_libsvm_scan(const char* buf, int64_t len, int64_t* n_rows,
                            int64_t* max_idx) {
    const char* p = buf;
    const char* end = buf + len;
    *n_rows = 0;
    *max_idx = -1;
    while (p < end) {
        const char* line_end = p;
        while (line_end < end && *line_end != '\n') ++line_end;
        if (!blank_line(p, line_end)) {
            ++*n_rows;
            const char* q = skip_ws(p, line_end);
            // skip the label token (validated by the fill pass)
            while (q < line_end && *q != ' ' && *q != '\t') ++q;
            while (q < line_end) {
                q = skip_ws(q, line_end);
                if (q >= line_end) break;
                const char* tok_end = q;
                const char* colon = nullptr;
                while (tok_end < line_end && *tok_end != ' '
                       && *tok_end != '\t') {
                    if (*tok_end == ':' && colon == nullptr) colon = tok_end;
                    ++tok_end;
                }
                if (colon != nullptr && colon > q
                    && all_digits(q, colon)) {
                    int64_t idx = strtoll(q, nullptr, 10);
                    if (idx < 0) return -1;   // Python writes col 0 here
                    if (idx > *max_idx) *max_idx = idx;
                }
                q = tok_end;
            }
        }
        p = line_end < end ? line_end + 1 : end;
    }
    return 0;
}

// Pass 2: fill out[n_rows, ncols] (row-major, PRE-ZEROED by the caller).
// Column 0 = label; feature idx lands at column idx + 1; tokens without
// a ':' (or with a non-integer index, e.g. qid:) are skipped — the
// Python parser's rules.  Returns rows written, or -1 on a malformed
// label/value token (caller falls back to the Python parser).
int64_t lgbmtpu_libsvm_fill(const char* buf, int64_t len, double* out,
                            int64_t n_rows, int64_t ncols) {
    const char* p = buf;
    const char* end = buf + len;
    int64_t row = 0;
    while (p < end && row < n_rows) {
        const char* line_end = p;
        while (line_end < end && *line_end != '\n') ++line_end;
        if (!blank_line(p, line_end)) {
            double* r = out + row * ncols;
            const char* q = skip_ws(p, line_end);
            const char* lab_end = q;
            while (lab_end < line_end && *lab_end != ' '
                   && *lab_end != '\t') ++lab_end;
            const char* le = lab_end;
            while (le > q && le[-1] == '\r') --le;
            bool ok = true;
            r[0] = parse_float_checked(q, le, &ok);
            if (!ok) return -1;
            q = lab_end;
            while (q < line_end) {
                q = skip_ws(q, line_end);
                const char* tok_end = q;
                const char* colon = nullptr;
                while (tok_end < line_end && *tok_end != ' '
                       && *tok_end != '\t') {
                    if (*tok_end == ':' && colon == nullptr) colon = tok_end;
                    ++tok_end;
                }
                if (colon != nullptr && colon > q
                    && all_digits(q, colon)) {
                    int64_t idx = strtoll(q, nullptr, 10);
                    const char* ve = tok_end;
                    while (ve > colon + 1 && ve[-1] == '\r') --ve;
                    double v = parse_float_checked(colon + 1, ve, &ok);
                    if (!ok) return -1;
                    if (idx >= 0 && idx + 1 < ncols) {
                        r[idx + 1] = v;
                    }
                }
                q = tok_end;
            }
            ++row;
        }
        p = line_end < end ? line_end + 1 : end;
    }
    return row;
}

}  // extern "C"
