// Native hot path of bin-bound construction.
//
// The reference computes bin bounds in C++ (GreedyFindBin, src/io/bin.cpp:
// 74-150); the Python re-expression in lightgbm_tpu/core/binning.py walks
// every distinct sample value in an interpreter loop (~0.4s per feature at
// the default 200k-row binning sample), which dominated dataset
// construction on the single-core host.  This file implements the SAME
// algorithm as the Python version (which is the spec; bounds must match it
// bit-for-bit) as a small ctypes-loaded shared object.
//
// Built on demand by lightgbm_tpu/core/native.py with the system g++.

#include <cmath>
#include <cstdint>
#include <limits>

namespace {

inline double next_after_up(double a) {
    return std::nextafter(a, std::numeric_limits<double>::infinity());
}

inline bool double_equal_ordered(double a, double b) {
    return b <= next_after_up(a);
}

// push a candidate bound if it is distinct from the previous one
inline void push_bound(double val, double* out, int64_t* n_out) {
    if (*n_out == 0 || !double_equal_ordered(out[*n_out - 1], val)) {
        out[(*n_out)++] = val;
    }
}

}  // namespace

extern "C" {

// distinct[n], counts[n] -> bounds written to out (caller allocates
// max_bin + 1 doubles); returns the number of bounds (always >= 1, the
// last is +inf).  Mirrors lightgbm_tpu.core.binning.greedy_find_bin.
int64_t lgbmtpu_greedy_find_bin(const double* distinct,
                                const int64_t* counts, int64_t n,
                                int64_t max_bin, int64_t total_cnt,
                                int64_t min_data_in_bin, double* out) {
    int64_t n_out = 0;
    if (n <= max_bin) {
        int64_t cur_cnt = 0;
        for (int64_t i = 0; i + 1 < n; ++i) {
            cur_cnt += counts[i];
            if (cur_cnt >= min_data_in_bin) {
                double val = next_after_up((distinct[i] + distinct[i + 1])
                                           / 2.0);
                int64_t before = n_out;
                push_bound(val, out, &n_out);
                if (n_out > before) cur_cnt = 0;
            }
        }
        out[n_out++] = std::numeric_limits<double>::infinity();
        return n_out;
    }

    if (min_data_in_bin > 0) {
        int64_t cap = total_cnt / min_data_in_bin;
        if (cap < max_bin) max_bin = cap;
        if (max_bin < 1) max_bin = 1;
    }
    double mean_bin_size = double(total_cnt) / double(max_bin);
    int64_t n_big = 0, big_cnt = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (double(counts[i]) >= mean_bin_size) {
            ++n_big;
            big_cnt += counts[i];
        }
    }
    int64_t rest_bin_cnt = max_bin - n_big;
    int64_t rest_sample_cnt = total_cnt - big_cnt;
    mean_bin_size = double(rest_sample_cnt)
        / double(rest_bin_cnt > 1 ? rest_bin_cnt : 1);

    // upper/lower bounds of the greedily-chosen value runs
    double* uppers = new double[max_bin];
    double* lowers = new double[max_bin + 1];
    int64_t bin_cnt = 0;
    lowers[0] = distinct[0];
    int64_t cur_cnt = 0;
    // the is_big test uses the ORIGINAL mean (the mask is computed once
    // up front in the Python spec), not the re-weighted running mean
    const double mean0 = double(total_cnt) / double(max_bin);
    for (int64_t i = 0; i + 1 < n; ++i) {
        const bool is_big_i = double(counts[i]) >= mean0;
        const bool is_big_next = double(counts[i + 1]) >= mean0;
        if (!is_big_i) rest_sample_cnt -= counts[i];
        cur_cnt += counts[i];
        if (is_big_i || double(cur_cnt) >= mean_bin_size ||
            (is_big_next && double(cur_cnt) >=
             (mean_bin_size * 0.5 > 1.0 ? mean_bin_size * 0.5 : 1.0))) {
            uppers[bin_cnt] = distinct[i];
            ++bin_cnt;
            lowers[bin_cnt] = distinct[i + 1];
            if (bin_cnt >= max_bin - 1) break;
            cur_cnt = 0;
            if (!is_big_i) {
                --rest_bin_cnt;
                mean_bin_size = double(rest_sample_cnt)
                    / double(rest_bin_cnt > 1 ? rest_bin_cnt : 1);
            }
        }
    }
    ++bin_cnt;
    for (int64_t i = 0; i + 1 < bin_cnt; ++i) {
        push_bound(next_after_up((uppers[i] + lowers[i + 1]) / 2.0),
                   out, &n_out);
    }
    out[n_out++] = std::numeric_limits<double>::infinity();
    delete[] uppers;
    delete[] lowers;
    return n_out;
}

// values[n] -> bins[n] for NUMERICAL mappers: first bound index with
// value <= bound, searched over bounds[0..n_search-1) (the vectorized
// np.searchsorted in BinMapper.value_to_bin); NaNs handled by the caller.
void lgbmtpu_values_to_bins(const double* values, int64_t n,
                            const double* bounds, int64_t n_search,
                            int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        double v = values[i];
        int64_t lo = 0, hi = n_search;     // search [lo, hi)
        while (lo < hi) {
            int64_t mid = (lo + hi) >> 1;
            if (bounds[mid] < v) lo = mid + 1; else hi = mid;
        }
        out[i] = int32_t(lo);
    }
}

}  // extern "C"
