// Native hot path of bin-bound construction.
//
// The reference computes bin bounds in C++ (GreedyFindBin, src/io/bin.cpp:
// 74-150); the Python re-expression in lightgbm_tpu/core/binning.py walks
// every distinct sample value in an interpreter loop (~0.4s per feature at
// the default 200k-row binning sample), which dominated dataset
// construction on the single-core host.  This file implements the SAME
// algorithm as the Python version (which is the spec; bounds must match it
// bit-for-bit) as a small ctypes-loaded shared object.
//
// Built on demand by lightgbm_tpu/core/native.py with the system g++.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>

namespace {

inline double next_after_up(double a) {
    return std::nextafter(a, std::numeric_limits<double>::infinity());
}

inline bool double_equal_ordered(double a, double b) {
    return b <= next_after_up(a);
}

// push a candidate bound if it is distinct from the previous one
inline void push_bound(double val, double* out, int64_t* n_out) {
    if (*n_out == 0 || !double_equal_ordered(out[*n_out - 1], val)) {
        out[(*n_out)++] = val;
    }
}

}  // namespace

extern "C" {

// distinct[n], counts[n] -> bounds written to out (caller allocates
// max_bin + 1 doubles); returns the number of bounds (always >= 1, the
// last is +inf).  Mirrors lightgbm_tpu.core.binning.greedy_find_bin.
int64_t lgbmtpu_greedy_find_bin(const double* distinct,
                                const int64_t* counts, int64_t n,
                                int64_t max_bin, int64_t total_cnt,
                                int64_t min_data_in_bin, double* out) {
    int64_t n_out = 0;
    if (n <= max_bin) {
        int64_t cur_cnt = 0;
        for (int64_t i = 0; i + 1 < n; ++i) {
            cur_cnt += counts[i];
            if (cur_cnt >= min_data_in_bin) {
                double val = next_after_up((distinct[i] + distinct[i + 1])
                                           / 2.0);
                int64_t before = n_out;
                push_bound(val, out, &n_out);
                if (n_out > before) cur_cnt = 0;
            }
        }
        out[n_out++] = std::numeric_limits<double>::infinity();
        return n_out;
    }

    if (min_data_in_bin > 0) {
        int64_t cap = total_cnt / min_data_in_bin;
        if (cap < max_bin) max_bin = cap;
        if (max_bin < 1) max_bin = 1;
    }
    double mean_bin_size = double(total_cnt) / double(max_bin);
    int64_t n_big = 0, big_cnt = 0;
    for (int64_t i = 0; i < n; ++i) {
        if (double(counts[i]) >= mean_bin_size) {
            ++n_big;
            big_cnt += counts[i];
        }
    }
    int64_t rest_bin_cnt = max_bin - n_big;
    int64_t rest_sample_cnt = total_cnt - big_cnt;
    mean_bin_size = double(rest_sample_cnt)
        / double(rest_bin_cnt > 1 ? rest_bin_cnt : 1);

    // upper/lower bounds of the greedily-chosen value runs
    double* uppers = new double[max_bin];
    double* lowers = new double[max_bin + 1];
    int64_t bin_cnt = 0;
    lowers[0] = distinct[0];
    int64_t cur_cnt = 0;
    // the is_big test uses the ORIGINAL mean (the mask is computed once
    // up front in the Python spec), not the re-weighted running mean
    const double mean0 = double(total_cnt) / double(max_bin);
    for (int64_t i = 0; i + 1 < n; ++i) {
        const bool is_big_i = double(counts[i]) >= mean0;
        const bool is_big_next = double(counts[i + 1]) >= mean0;
        if (!is_big_i) rest_sample_cnt -= counts[i];
        cur_cnt += counts[i];
        if (is_big_i || double(cur_cnt) >= mean_bin_size ||
            (is_big_next && double(cur_cnt) >=
             (mean_bin_size * 0.5 > 1.0 ? mean_bin_size * 0.5 : 1.0))) {
            uppers[bin_cnt] = distinct[i];
            ++bin_cnt;
            lowers[bin_cnt] = distinct[i + 1];
            if (bin_cnt >= max_bin - 1) break;
            cur_cnt = 0;
            if (!is_big_i) {
                --rest_bin_cnt;
                mean_bin_size = double(rest_sample_cnt)
                    / double(rest_bin_cnt > 1 ? rest_bin_cnt : 1);
            }
        }
    }
    ++bin_cnt;
    for (int64_t i = 0; i + 1 < bin_cnt; ++i) {
        push_bound(next_after_up((uppers[i] + lowers[i + 1]) / 2.0),
                   out, &n_out);
    }
    out[n_out++] = std::numeric_limits<double>::infinity();
    delete[] uppers;
    delete[] lowers;
    return n_out;
}

// values[n] -> bins[n] for NUMERICAL mappers: first bound index with
// value <= bound, searched over bounds[0..n_search-1) (the vectorized
// np.searchsorted in BinMapper.value_to_bin); NaNs handled by the caller.
void lgbmtpu_values_to_bins(const double* values, int64_t n,
                            const double* bounds, int64_t n_search,
                            int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        double v = values[i];
        int64_t lo = 0, hi = n_search;     // search [lo, hi)
        while (lo < hi) {
            int64_t mid = (lo + hi) >> 1;
            if (bounds[mid] < v) lo = mid + 1; else hi = mid;
        }
        out[i] = int32_t(lo);
    }
}

}  // extern "C"

namespace {

// cache-blocked matrix quantization; NaN routed per missing_type.
// T = float or double input; OutT = uint8_t or uint16_t bins.
//
// bin = #{b : ub[b] < v} == searchsorted(ub, v, side=left).  For the
// common narrow-bin case the count runs as a BRANCHLESS linear scan
// the compiler vectorizes (a binary search mispredicts ~every level on
// shuffled data — measured 42 ns/value; the SIMD count is ~6 ns); wide
// bound sets (u16 datasets) keep a branchless binary search.
constexpr int64_t kQChunk = 2048;
constexpr int64_t kLinearMax = 128;

template <typename T, typename OutT>
void quantize_rows(const T* data, int64_t n, int64_t f_total,
                   const int64_t* feat_idx, int64_t n_used,
                   const double* bounds_flat, const int64_t* bounds_off,
                   const int32_t* missing_type, const int32_t* num_bin,
                   OutT* out) {
    double buf[kQChunk];
    for (int64_t c0 = 0; c0 < n; c0 += kQChunk) {
        int64_t c = std::min(kQChunk, n - c0);
        for (int64_t j = 0; j < n_used; ++j) {
            const T* col = data + c0 * f_total + feat_idx[j];
            const double* ub = bounds_flat + bounds_off[j];
            const int64_t nb = bounds_off[j + 1] - bounds_off[j];
            const bool nan_last = missing_type[j] == 2;
            const OutT nan_bin = OutT(num_bin[j] - 1);
            OutT* o = out + c0 * n_used + j;
            // strided gather to a contiguous scratch (NaN -> 0.0, the
            // value_to_bin substitution; core/binning.py:382)
            for (int64_t i = 0; i < c; ++i) {
                double v = double(col[i * f_total]);
                buf[i] = std::isnan(v) ? 0.0 : v;
            }
            if (nb <= kLinearMax) {
                for (int64_t i = 0; i < c; ++i) {
                    const double v = buf[i];
                    int64_t cnt = 0;
                    for (int64_t b = 0; b < nb; ++b) {
                        cnt += ub[b] < v;          // vectorized count
                    }
                    o[i * n_used] = OutT(cnt);
                }
            } else {
                for (int64_t i = 0; i < c; ++i) {
                    const double v = buf[i];
                    const double* base = ub;
                    int64_t len = nb;
                    while (len > 1) {              // branchless lower_bound
                        int64_t half = len >> 1;
                        base += (base[half - 1] < v) ? half : 0;
                        len -= half;
                    }
                    o[i * n_used] =
                        OutT((base - ub) + (nb > 0 && base[0] < v ? 1 : 0));
                }
            }
            if (nan_last) {
                for (int64_t i = 0; i < c; ++i) {
                    if (std::isnan(double(col[i * f_total]))) {
                        o[i * n_used] = nan_bin;
                    }
                }
            }
        }
    }
}

// f32 fast path: thresholds t[b] are the smallest floats whose f64
// value exceeds the column's f64 bound, so the f64 rule
// "count ub[b] < (double)v" is EXACTLY "count v >= t[b]" in pure f32
// (the caller precomputes t; exactness argued in core/native.py).
// One f32 SIMD lane carries 2x the f64 width and skips the
// double-conversion gather.
void quantize_rows_f32_thr(const float* data, int64_t n, int64_t f_total,
                           const int64_t* feat_idx, int64_t n_used,
                           const float* thr_flat,
                           const int64_t* bounds_off,
                           const int32_t* missing_type,
                           const int32_t* num_bin, uint8_t* out) {
    float buf[kQChunk];
    for (int64_t c0 = 0; c0 < n; c0 += kQChunk) {
        int64_t c = std::min(kQChunk, n - c0);
        for (int64_t j = 0; j < n_used; ++j) {
            const float* col = data + c0 * f_total + feat_idx[j];
            const float* thr = thr_flat + bounds_off[j];
            const int64_t nb = bounds_off[j + 1] - bounds_off[j];
            const bool nan_last = missing_type[j] == 2;
            const uint8_t nan_bin = uint8_t(num_bin[j] - 1);
            uint8_t* o = out + c0 * n_used + j;
            for (int64_t i = 0; i < c; ++i) {
                float v = col[i * f_total];
                buf[i] = std::isnan(v) ? 0.0f : v;
            }
            for (int64_t i = 0; i < c; ++i) {
                const float v = buf[i];
                int32_t cnt = 0;
                for (int64_t b = 0; b < nb; ++b) {
                    cnt += v >= thr[b];
                }
                o[i * n_used] = uint8_t(cnt);
            }
            if (nan_last) {
                for (int64_t i = 0; i < c; ++i) {
                    if (std::isnan(col[i * f_total])) {
                        o[i * n_used] = nan_bin;
                    }
                }
            }
        }
    }
}

}  // namespace

extern "C" {

// f32-input, u8-output, narrow-bounds fast path (see
// quantize_rows_f32_thr above); thr_flat are the caller-precomputed
// exact f32 thresholds.
void lgbmtpu_quantize_rows_f32(const float* data, int64_t n,
                               int64_t f_total, const int64_t* feat_idx,
                               int64_t n_used, const float* thr_flat,
                               const int64_t* bounds_off,
                               const int32_t* missing_type,
                               const int32_t* num_bin, uint8_t* out) {
    quantize_rows_f32_thr(data, n, f_total, feat_idx, n_used, thr_flat,
                          bounds_off, missing_type, num_bin, out);
}

// Whole-matrix quantization (the ValueToBin application loop the
// reference runs in C++, src/io/dataset_loader.cpp push paths): one
// cache-friendly pass over the row-major [n, f_total] data instead of
// one strided column copy + searchsorted per feature.  ``bounds_off``
// has n_used + 1 entries delimiting each used column's TRUNCATED bound
// slice (ub[:max(n_search - 1, 0)]); ``is_f64``/``is_u16`` pick the
// input/output widths.
void lgbmtpu_quantize_rows(const void* data, int64_t is_f64, int64_t n,
                           int64_t f_total, const int64_t* feat_idx,
                           int64_t n_used, const double* bounds_flat,
                           const int64_t* bounds_off,
                           const int32_t* missing_type,
                           const int32_t* num_bin, int64_t is_u16,
                           void* out) {
    if (is_f64) {
        if (is_u16)
            quantize_rows((const double*)data, n, f_total, feat_idx,
                          n_used, bounds_flat, bounds_off, missing_type,
                          num_bin, (uint16_t*)out);
        else
            quantize_rows((const double*)data, n, f_total, feat_idx,
                          n_used, bounds_flat, bounds_off, missing_type,
                          num_bin, (uint8_t*)out);
    } else {
        if (is_u16)
            quantize_rows((const float*)data, n, f_total, feat_idx,
                          n_used, bounds_flat, bounds_off, missing_type,
                          num_bin, (uint16_t*)out);
        else
            quantize_rows((const float*)data, n, f_total, feat_idx,
                          n_used, bounds_flat, bounds_off, missing_type,
                          num_bin, (uint8_t*)out);
    }
}

}  // extern "C"
