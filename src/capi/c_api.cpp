/*
 * LightGBM C API contract for the TPU framework.
 *
 * Implements the ~60 LGBM_* entry points of the reference
 * (include/LightGBM/c_api.h:40-1030, src/c_api.cpp:98-1831) as a native
 * shared library.  The compute engine is the in-process JAX/TPU stack, so
 * each entry point marshals its raw-pointer arguments into the embedded
 * CPython interpreter and dispatches to lightgbm_tpu.capi (the bridge
 * module), which wraps the caller's buffers with numpy views (zero copy)
 * and drives lightgbm_tpu.basic.Dataset / Booster.
 *
 * Contract pieces kept from the reference:
 *   - opaque DatasetHandle / BoosterHandle (here: integer ids minted by
 *     the bridge, cast through void*);
 *   - thread-local last-error ring: LGBM_GetLastError
 *     (reference src/c_api.cpp:57-64);
 *   - 0 / -1 return convention with API_BEGIN/API_END guards
 *     (reference include/LightGBM/c_api.h:1040-1060);
 *   - dual-mode embedding: when loaded from a host C program the library
 *     initializes CPython itself; when loaded inside a Python process
 *     (ctypes) it attaches to the existing interpreter via the GIL.
 */

#include <Python.h>

#include <cstdarg>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

#ifndef LIGHTGBM_C_EXPORT
#define LIGHTGBM_C_EXPORT extern "C" __attribute__((visibility("default")))
#endif

typedef void* DatasetHandle;
typedef void* BoosterHandle;

/* ------------------------------------------------------------------ */
/* error plumbing                                                     */
/* ------------------------------------------------------------------ */

static thread_local std::string g_last_error = "Everything is fine";

LIGHTGBM_C_EXPORT const char* LGBM_GetLastError() {
  return g_last_error.c_str();
}

static void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  g_last_error = msg;
}

/* ------------------------------------------------------------------ */
/* interpreter management                                             */
/* ------------------------------------------------------------------ */

static std::once_flag g_py_once;
static bool g_we_initialized = false;

static void ensure_interpreter() {
  std::call_once(g_py_once, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      g_we_initialized = true;
      /* release the GIL acquired by Py_Initialize so that GILGuard's
         PyGILState_Ensure works uniformly from any thread */
      PyEval_SaveThread();
    }
  });
}

class GILGuard {
 public:
  GILGuard() {
    ensure_interpreter();
    state_ = PyGILState_Ensure();
  }
  ~GILGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

static PyObject* bridge_module() {
  static PyObject* mod = nullptr;  /* leaked on purpose; lives forever */
  if (mod == nullptr) {
    mod = PyImport_ImportModule("lightgbm_tpu.capi");
  }
  return mod;
}

/* Call lightgbm_tpu.capi.<fn>(args...) built with Py_BuildValue(fmt).
   Returns a NEW reference or nullptr (python error already recorded). */
static PyObject* bridge_call_v(const char* fn, const char* fmt, va_list ap) {
  PyObject* mod = bridge_module();
  if (mod == nullptr) return nullptr;
  PyObject* func = PyObject_GetAttrString(mod, fn);
  if (func == nullptr) return nullptr;
  PyObject* args = Py_VaBuildValue(fmt, ap);
  if (args == nullptr) {
    Py_DECREF(func);
    return nullptr;
  }
  if (!PyTuple_Check(args)) {  /* single argument case */
    PyObject* t = PyTuple_Pack(1, args);
    Py_DECREF(args);
    args = t;
    if (args == nullptr) {
      Py_DECREF(func);
      return nullptr;
    }
  }
  PyObject* out = PyObject_CallObject(func, args);
  Py_DECREF(args);
  Py_DECREF(func);
  return out;
}

static PyObject* bridge_call(const char* fn, const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  PyObject* out = bridge_call_v(fn, fmt, ap);
  va_end(ap);
  return out;
}

/* run a bridge call that returns None / ignored value */
static int run_void(const char* fn, const char* fmt, ...) {
  GILGuard gil;
  va_list ap;
  va_start(ap, fmt);
  PyObject* out = bridge_call_v(fn, fmt, ap);
  va_end(ap);
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(out);
  return 0;
}

/* run a bridge call that returns one integer (handle id or scalar) */
static int run_i64(const char* fn, int64_t* result, const char* fmt, ...) {
  GILGuard gil;
  va_list ap;
  va_start(ap, fmt);
  PyObject* out = bridge_call_v(fn, fmt, ap);
  va_end(ap);
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  *result = PyLong_AsLongLong(out);
  Py_DECREF(out);
  if (PyErr_Occurred()) {
    set_error_from_python();
    return -1;
  }
  return 0;
}

static inline int64_t H(const void* handle) {
  return static_cast<int64_t>(reinterpret_cast<intptr_t>(handle));
}

static inline void* mk_handle(int64_t id) {
  return reinterpret_cast<void*>(static_cast<intptr_t>(id));
}

static inline unsigned long long A(const void* p) {
  return static_cast<unsigned long long>(reinterpret_cast<uintptr_t>(p));
}

/* copy a python str into (buffer_len, out_len, out_str) */
static int copy_string_out(PyObject* s, int64_t buffer_len, int64_t* out_len,
                           char* out_str) {
  Py_ssize_t n = 0;
  const char* c = PyUnicode_AsUTF8AndSize(s, &n);
  if (c == nullptr) return -1;
  *out_len = static_cast<int64_t>(n) + 1;
  if (out_str != nullptr && buffer_len >= n + 1) {
    std::memcpy(out_str, c, n + 1);
  }
  return 0;
}

/* ================================================================== */
/* Dataset interface                                                  */
/* ================================================================== */

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromFile(const char* filename,
                                                 const char* parameters,
                                                 const DatasetHandle reference,
                                                 DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_create_from_file", &id, "(szL)", filename,
                   parameters, H(reference));
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromSampledColumn(
    double** sample_data, int** sample_indices, int32_t ncol,
    const int* num_per_col, int32_t num_sample_row, int32_t num_total_row,
    const char* parameters, DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_create_from_sampled_column", &id, "(KKiKiis)",
                   A(sample_data), A(sample_indices), (int)ncol,
                   A(num_per_col), (int)num_sample_row, (int)num_total_row,
                   parameters);
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateByReference(
    const DatasetHandle reference, int64_t num_total_row,
    DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_create_by_reference", &id, "(LL)", H(reference),
                   (long long)num_total_row);
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetPushRows(DatasetHandle dataset,
                                           const void* data, int data_type,
                                           int32_t nrow, int32_t ncol,
                                           int32_t start_row) {
  return run_void("dataset_push_rows", "(LKiiii)", H(dataset), A(data),
                  data_type, (int)nrow, (int)ncol, (int)start_row);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetPushRowsByCSR(
    DatasetHandle dataset, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type, int64_t nindptr,
    int64_t nelem, int64_t num_col, int64_t start_row) {
  return run_void("dataset_push_rows_by_csr", "(LKiKKiLLLL)", H(dataset),
                  A(indptr), indptr_type, A(indices), A(data), data_type,
                  (long long)nindptr, (long long)nelem, (long long)num_col,
                  (long long)start_row);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromCSR(
    const void* indptr, int indptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t nindptr, int64_t nelem,
    int64_t num_col, const char* parameters, const DatasetHandle reference,
    DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_create_from_csr", &id, "(KiKKiLLLsL)", A(indptr),
                   indptr_type, A(indices), A(data), data_type,
                   (long long)nindptr, (long long)nelem, (long long)num_col,
                   parameters, H(reference));
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromCSRFunc(
    void* get_row_funptr, int num_rows, int64_t num_col,
    const char* parameters, const DatasetHandle reference,
    DatasetHandle* out) {
  /* the reference receives a std::function<void(int,
     std::vector<std::pair<int, double>>&)>* here (c_api.cpp:528);
     iterate it on the C++ side and hand the bridge a materialized CSR */
  using RowFn = std::function<void(int, std::vector<std::pair<int, double>>&)>;
  RowFn& fn = *static_cast<RowFn*>(get_row_funptr);
  std::vector<int64_t> indptr(1, 0);
  std::vector<int32_t> indices;
  std::vector<double> values;
  std::vector<std::pair<int, double>> row;
  for (int i = 0; i < num_rows; ++i) {
    row.clear();
    fn(i, row);
    for (auto& kv : row) {
      indices.push_back(kv.first);
      values.push_back(kv.second);
    }
    indptr.push_back(static_cast<int64_t>(indices.size()));
  }
  return LGBM_DatasetCreateFromCSR(indptr.data(), 3 /*int64*/,
                                   indices.data(), values.data(),
                                   1 /*float64*/, (int64_t)indptr.size(),
                                   (int64_t)values.size(), num_col,
                                   parameters, reference, out);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromCSC(
    const void* col_ptr, int col_ptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t ncol_ptr, int64_t nelem,
    int64_t num_row, const char* parameters, const DatasetHandle reference,
    DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_create_from_csc", &id, "(KiKKiLLLsL)", A(col_ptr),
                   col_ptr_type, A(indices), A(data), data_type,
                   (long long)ncol_ptr, (long long)nelem, (long long)num_row,
                   parameters, H(reference));
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromMat(const void* data,
                                                int data_type, int32_t nrow,
                                                int32_t ncol,
                                                int is_row_major,
                                                const char* parameters,
                                                const DatasetHandle reference,
                                                DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_create_from_mat", &id, "(KiiiisL)", A(data),
                   data_type, (int)nrow, (int)ncol, is_row_major, parameters,
                   H(reference));
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromMats(
    int32_t nmat, const void** data, int data_type, int32_t* nrow,
    int32_t ncol, int is_row_major, const char* parameters,
    const DatasetHandle reference, DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_create_from_mats", &id, "(iKiKiisL)", (int)nmat,
                   A(data), data_type, A(nrow), (int)ncol, is_row_major,
                   parameters, H(reference));
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetGetSubset(const DatasetHandle handle,
                                            const int32_t* used_row_indices,
                                            int32_t num_used_row_indices,
                                            const char* parameters,
                                            DatasetHandle* out) {
  int64_t id;
  int rc = run_i64("dataset_get_subset", &id, "(LKis)", H(handle),
                   A(used_row_indices), (int)num_used_row_indices,
                   parameters);
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                                  const char** feature_names,
                                                  int num_feature_names) {
  GILGuard gil;
  PyObject* lst = PyList_New(num_feature_names);
  if (lst == nullptr) {
    set_error_from_python();
    return -1;
  }
  for (int i = 0; i < num_feature_names; ++i) {
    PyList_SetItem(lst, i, PyUnicode_FromString(feature_names[i]));
  }
  PyObject* out = bridge_call("dataset_set_feature_names", "(LO)", H(handle),
                              lst);
  Py_DECREF(lst);
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(out);
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetGetFeatureNames(DatasetHandle handle,
                                                  char** feature_names,
                                                  int* num_feature_names) {
  GILGuard gil;
  PyObject* out = bridge_call("dataset_get_feature_names", "(L)", H(handle));
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyList_Size(out);
  *num_feature_names = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(out, i));
    if (s != nullptr && feature_names != nullptr) {
      std::strcpy(feature_names[i], s);  /* caller pre-allocates, same
                                            contract as c_api.cpp:712 */
    }
  }
  Py_DECREF(out);
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetFree(DatasetHandle handle) {
  return run_void("free_handle", "(L)", H(handle));
}

LIGHTGBM_C_EXPORT int LGBM_DatasetSaveBinary(DatasetHandle handle,
                                             const char* filename) {
  return run_void("dataset_save_binary", "(Ls)", H(handle), filename);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetDumpText(DatasetHandle handle,
                                           const char* filename) {
  return run_void("dataset_dump_text", "(Ls)", H(handle), filename);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetSetField(DatasetHandle handle,
                                           const char* field_name,
                                           const void* field_data,
                                           int num_element, int type) {
  return run_void("dataset_set_field", "(LsKii)", H(handle), field_name,
                  A(field_data), num_element, type);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetGetField(DatasetHandle handle,
                                           const char* field_name,
                                           int* out_len,
                                           const void** out_ptr,
                                           int* out_type) {
  GILGuard gil;
  PyObject* out = bridge_call("dataset_get_field", "(Ls)", H(handle),
                              field_name);
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  /* (addr, len, type) with the buffer owned by the dataset object */
  unsigned long long addr = PyLong_AsUnsignedLongLong(
      PyTuple_GetItem(out, 0));
  *out_len = (int)PyLong_AsLong(PyTuple_GetItem(out, 1));
  *out_type = (int)PyLong_AsLong(PyTuple_GetItem(out, 2));
  *out_ptr = reinterpret_cast<const void*>(static_cast<uintptr_t>(addr));
  Py_DECREF(out);
  if (PyErr_Occurred()) {
    set_error_from_python();
    return -1;
  }
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetUpdateParam(DatasetHandle handle,
                                              const char* parameters) {
  return run_void("dataset_update_param", "(Ls)", H(handle), parameters);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetGetNumData(DatasetHandle handle, int* out) {
  int64_t v;
  int rc = run_i64("dataset_get_num_data", &v, "(L)", H(handle));
  if (rc == 0) *out = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetGetNumFeature(DatasetHandle handle,
                                                int* out) {
  int64_t v;
  int rc = run_i64("dataset_get_num_feature", &v, "(L)", H(handle));
  if (rc == 0) *out = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetAddFeaturesFrom(DatasetHandle target,
                                                  DatasetHandle source) {
  return run_void("dataset_add_features_from", "(LL)", H(target), H(source));
}

/* ================================================================== */
/* Booster interface                                                  */
/* ================================================================== */

LIGHTGBM_C_EXPORT int LGBM_BoosterCreate(const DatasetHandle train_data,
                                         const char* parameters,
                                         BoosterHandle* out) {
  int64_t id;
  int rc = run_i64("booster_create", &id, "(Ls)", H(train_data), parameters);
  if (rc == 0) *out = mk_handle(id);
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterCreateFromModelfile(const char* filename,
                                                      int* out_num_iterations,
                                                      BoosterHandle* out) {
  GILGuard gil;
  PyObject* r = bridge_call("booster_create_from_modelfile", "(s)", filename);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out = mk_handle(PyLong_AsLongLong(PyTuple_GetItem(r, 0)));
  *out_num_iterations = (int)PyLong_AsLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return PyErr_Occurred() ? (set_error_from_python(), -1) : 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterLoadModelFromString(
    const char* model_str, int* out_num_iterations, BoosterHandle* out) {
  GILGuard gil;
  PyObject* r = bridge_call("booster_load_model_from_string", "(s)",
                            model_str);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out = mk_handle(PyLong_AsLongLong(PyTuple_GetItem(r, 0)));
  *out_num_iterations = (int)PyLong_AsLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return PyErr_Occurred() ? (set_error_from_python(), -1) : 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterFree(BoosterHandle handle) {
  return run_void("free_handle", "(L)", H(handle));
}

LIGHTGBM_C_EXPORT int LGBM_BoosterShuffleModels(BoosterHandle handle,
                                                int start_iter, int end_iter) {
  return run_void("booster_shuffle_models", "(Lii)", H(handle), start_iter,
                  end_iter);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterMerge(BoosterHandle handle,
                                        BoosterHandle other_handle) {
  return run_void("booster_merge", "(LL)", H(handle), H(other_handle));
}

LIGHTGBM_C_EXPORT int LGBM_BoosterAddValidData(BoosterHandle handle,
                                               const DatasetHandle valid) {
  return run_void("booster_add_valid_data", "(LL)", H(handle), H(valid));
}

LIGHTGBM_C_EXPORT int LGBM_BoosterResetTrainingData(
    BoosterHandle handle, const DatasetHandle train_data) {
  return run_void("booster_reset_training_data", "(LL)", H(handle),
                  H(train_data));
}

LIGHTGBM_C_EXPORT int LGBM_BoosterResetParameter(BoosterHandle handle,
                                                 const char* parameters) {
  return run_void("booster_reset_parameter", "(Ls)", H(handle), parameters);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetNumClasses(BoosterHandle handle,
                                                int* out_len) {
  int64_t v;
  int rc = run_i64("booster_get_num_classes", &v, "(L)", H(handle));
  if (rc == 0) *out_len = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                                int* is_finished) {
  int64_t v;
  int rc = run_i64("booster_update_one_iter", &v, "(L)", H(handle));
  if (rc == 0) *is_finished = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterRefit(BoosterHandle handle,
                                        const int32_t* leaf_preds,
                                        int32_t nrow, int32_t ncol) {
  return run_void("booster_refit", "(LKii)", H(handle), A(leaf_preds),
                  (int)nrow, (int)ncol);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle,
                                                      const float* grad,
                                                      const float* hess,
                                                      int* is_finished) {
  int64_t v;
  int rc = run_i64("booster_update_one_iter_custom", &v, "(LKK)", H(handle),
                   A(grad), A(hess));
  if (rc == 0) *is_finished = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  return run_void("booster_rollback_one_iter", "(L)", H(handle));
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                                      int* out_iteration) {
  int64_t v;
  int rc = run_i64("booster_get_current_iteration", &v, "(L)", H(handle));
  if (rc == 0) *out_iteration = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterNumModelPerIteration(
    BoosterHandle handle, int* out_tree_per_iteration) {
  int64_t v;
  int rc = run_i64("booster_num_model_per_iteration", &v, "(L)", H(handle));
  if (rc == 0) *out_tree_per_iteration = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle,
                                                     int* out_models) {
  int64_t v;
  int rc = run_i64("booster_number_of_total_model", &v, "(L)", H(handle));
  if (rc == 0) *out_models = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetEvalCounts(BoosterHandle handle,
                                                int* out_len) {
  int64_t v;
  int rc = run_i64("booster_get_eval_counts", &v, "(L)", H(handle));
  if (rc == 0) *out_len = (int)v;
  return rc;
}

static int strings_out(PyObject* lst, int* out_len, char** out_strs) {
  Py_ssize_t n = PyList_Size(lst);
  *out_len = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
    if (s != nullptr && out_strs != nullptr) std::strcpy(out_strs[i], s);
  }
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetEvalNames(BoosterHandle handle,
                                               int* out_len,
                                               char** out_strs) {
  GILGuard gil;
  PyObject* out = bridge_call("booster_get_eval_names", "(L)", H(handle));
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  strings_out(out, out_len, out_strs);
  Py_DECREF(out);
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetFeatureNames(BoosterHandle handle,
                                                  int* out_len,
                                                  char** out_strs) {
  GILGuard gil;
  PyObject* out = bridge_call("booster_get_feature_names", "(L)", H(handle));
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  strings_out(out, out_len, out_strs);
  Py_DECREF(out);
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetNumFeature(BoosterHandle handle,
                                                int* out_len) {
  int64_t v;
  int rc = run_i64("booster_get_num_feature", &v, "(L)", H(handle));
  if (rc == 0) *out_len = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx,
                                          int* out_len, double* out_results) {
  int64_t v;
  int rc = run_i64("booster_get_eval", &v, "(LiK)", H(handle), data_idx,
                   A(out_results));
  if (rc == 0) *out_len = (int)v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetNumPredict(BoosterHandle handle,
                                                int data_idx,
                                                int64_t* out_len) {
  int64_t v;
  int rc = run_i64("booster_get_num_predict", &v, "(Li)", H(handle),
                   data_idx);
  if (rc == 0) *out_len = v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetPredict(BoosterHandle handle,
                                             int data_idx, int64_t* out_len,
                                             double* out_result) {
  int64_t v;
  int rc = run_i64("booster_get_predict", &v, "(LiK)", H(handle), data_idx,
                   A(out_result));
  if (rc == 0) *out_len = v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForFile(
    BoosterHandle handle, const char* data_filename, int data_has_header,
    int predict_type, int num_iteration, const char* parameter,
    const char* result_filename) {
  return run_void("booster_predict_for_file", "(Lsiiiss)", H(handle),
                  data_filename, data_has_header, predict_type,
                  num_iteration, parameter, result_filename);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterCalcNumPredict(BoosterHandle handle,
                                                 int num_row,
                                                 int predict_type,
                                                 int num_iteration,
                                                 int64_t* out_len) {
  int64_t v;
  int rc = run_i64("booster_calc_num_predict", &v, "(Liii)", H(handle),
                   num_row, predict_type, num_iteration);
  if (rc == 0) *out_len = v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForCSR(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type, int64_t nindptr,
    int64_t nelem, int64_t num_col, int predict_type, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  int64_t v;
  int rc = run_i64("booster_predict_for_csr", &v, "(LKiKKiLLLiisK)",
                   H(handle), A(indptr), indptr_type, A(indices), A(data),
                   data_type, (long long)nindptr, (long long)nelem,
                   (long long)num_col, predict_type, num_iteration,
                   parameter, A(out_result));
  if (rc == 0) *out_len = v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForCSRSingleRow(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type, int64_t nindptr,
    int64_t nelem, int64_t num_col, int predict_type, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  return LGBM_BoosterPredictForCSR(handle, indptr, indptr_type, indices,
                                   data, data_type, nindptr, nelem, num_col,
                                   predict_type, num_iteration, parameter,
                                   out_len, out_result);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForCSC(
    BoosterHandle handle, const void* col_ptr, int col_ptr_type,
    const int32_t* indices, const void* data, int data_type, int64_t ncol_ptr,
    int64_t nelem, int64_t num_row, int predict_type, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  int64_t v;
  int rc = run_i64("booster_predict_for_csc", &v, "(LKiKKiLLLiisK)",
                   H(handle), A(col_ptr), col_ptr_type, A(indices), A(data),
                   data_type, (long long)ncol_ptr, (long long)nelem,
                   (long long)num_row, predict_type, num_iteration,
                   parameter, A(out_result));
  if (rc == 0) *out_len = v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForMat(
    BoosterHandle handle, const void* data, int data_type, int32_t nrow,
    int32_t ncol, int is_row_major, int predict_type, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  int64_t v;
  int rc = run_i64("booster_predict_for_mat", &v, "(LKiiiiiisK)", H(handle),
                   A(data), data_type, (int)nrow, (int)ncol, is_row_major,
                   predict_type, num_iteration, parameter, A(out_result));
  if (rc == 0) *out_len = v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForMatSingleRow(
    BoosterHandle handle, const void* data, int data_type, int ncol,
    int is_row_major, int predict_type, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  return LGBM_BoosterPredictForMat(handle, data, data_type, 1, ncol,
                                   is_row_major, predict_type, num_iteration,
                                   parameter, out_len, out_result);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForMats(
    BoosterHandle handle, const void** data, int data_type, int32_t nrow,
    int32_t ncol, int predict_type, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  int64_t v;
  int rc = run_i64("booster_predict_for_mats", &v, "(LKiiiiisK)", H(handle),
                   A(data), data_type, (int)nrow, (int)ncol, predict_type,
                   num_iteration, parameter, A(out_result));
  if (rc == 0) *out_len = v;
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterSaveModel(BoosterHandle handle,
                                            int start_iteration,
                                            int num_iteration,
                                            const char* filename) {
  return run_void("booster_save_model", "(Liis)", H(handle), start_iteration,
                  num_iteration, filename);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterSaveModelToString(
    BoosterHandle handle, int start_iteration, int num_iteration,
    int64_t buffer_len, int64_t* out_len, char* out_str) {
  GILGuard gil;
  PyObject* s = bridge_call("booster_save_model_to_string", "(Lii)",
                            H(handle), start_iteration, num_iteration);
  if (s == nullptr) {
    set_error_from_python();
    return -1;
  }
  int rc = copy_string_out(s, buffer_len, out_len, out_str);
  Py_DECREF(s);
  if (rc != 0) set_error_from_python();
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterDumpModel(BoosterHandle handle,
                                            int start_iteration,
                                            int num_iteration,
                                            int64_t buffer_len,
                                            int64_t* out_len, char* out_str) {
  GILGuard gil;
  PyObject* s = bridge_call("booster_dump_model", "(Lii)", H(handle),
                            start_iteration, num_iteration);
  if (s == nullptr) {
    set_error_from_python();
    return -1;
  }
  int rc = copy_string_out(s, buffer_len, out_len, out_str);
  Py_DECREF(s);
  if (rc != 0) set_error_from_python();
  return rc;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetLeafValue(BoosterHandle handle,
                                               int tree_idx, int leaf_idx,
                                               double* out_val) {
  GILGuard gil;
  PyObject* out = bridge_call("booster_get_leaf_value", "(Lii)", H(handle),
                              tree_idx, leaf_idx);
  if (out == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_val = PyFloat_AsDouble(out);
  Py_DECREF(out);
  return PyErr_Occurred() ? (set_error_from_python(), -1) : 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterSetLeafValue(BoosterHandle handle,
                                               int tree_idx, int leaf_idx,
                                               double val) {
  return run_void("booster_set_leaf_value", "(Liid)", H(handle), tree_idx,
                  leaf_idx, val);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterFeatureImportance(BoosterHandle handle,
                                                    int num_iteration,
                                                    int importance_type,
                                                    double* out_results) {
  return run_void("booster_feature_importance", "(LiiK)", H(handle),
                  num_iteration, importance_type, A(out_results));
}

/* ================================================================== */
/* Network interface                                                  */
/* ================================================================== */

LIGHTGBM_C_EXPORT int LGBM_NetworkInit(const char* machines,
                                       int local_listen_port,
                                       int listen_time_out,
                                       int num_machines) {
  return run_void("network_init", "(siii)", machines, local_listen_port,
                  listen_time_out, num_machines);
}

LIGHTGBM_C_EXPORT int LGBM_NetworkFree() {
  return run_void("network_free", "()");
}

LIGHTGBM_C_EXPORT int LGBM_NetworkInitWithFunctions(
    int num_machines, int rank, void* reduce_scatter_ext_fun,
    void* allgather_ext_fun) {
  return run_void("network_init_with_functions", "(iiKK)", num_machines,
                  rank, A(reduce_scatter_ext_fun), A(allgather_ext_fun));
}
