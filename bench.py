"""Benchmark: HIGGS-proxy binary training throughput on one TPU chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The box has zero egress, so the real HIGGS file (10.5M x 28 dense floats)
is proxied by synthetic data with the same feature count and the reference
GPU-benchmark config (max_bin=63, num_leaves=255, lr=0.1,
docs/GPU-Performance.rst:110-127).  Steady-state per-iteration time is
measured after warmup and extrapolated to the reference's 500 iterations.

Baseline: the reference's published HIGGS CPU time is 238.505 s for 500
iters on 10.5M rows (docs/Experiments.rst:101-116) = 22.715 s row-scaled to
this benchmark's 1M rows.  vs_baseline = ours / baseline (< 1.0 beats the
reference CPU; the GPU learner's wall-clock is only published as a chart).
"""

import json
import sys
import time

import numpy as np

N_ROWS = 1_000_000
N_FEATURES = 28
MAX_BIN = 63
NUM_LEAVES = 255
WARMUP_ITERS = 3
MEASURE_ITERS = 12
TOTAL_ITERS_REF = 500
BASELINE_500_ITERS_S = 238.505 * (N_ROWS / 10_500_000)


def main():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.core.dataset import TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objective import create_objective

    rng = np.random.RandomState(42)
    X = rng.normal(size=(N_ROWS, N_FEATURES)).astype(np.float32)
    logit = (2.0 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
             + 0.5 * np.sin(3 * X[:, 4]))
    y = (logit + rng.normal(size=N_ROWS) * 0.5 > 0).astype(np.float64)

    cfg = Config(objective="binary", num_leaves=NUM_LEAVES, max_bin=MAX_BIN,
                 learning_rate=0.1, min_sum_hessian_in_leaf=100.0,
                 verbosity=-1)
    t0 = time.time()
    ds = TpuDataset.from_numpy(X, y, config=cfg)
    t_bin = time.time() - t0

    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    booster = GBDT(cfg, ds, obj)

    for _ in range(WARMUP_ITERS):
        booster.train_one_iter()

    t0 = time.time()
    for _ in range(MEASURE_ITERS):
        booster.train_one_iter()
    import jax
    jax.block_until_ready(booster.train_score)
    per_iter = (time.time() - t0) / MEASURE_ITERS
    total_500 = per_iter * TOTAL_ITERS_REF

    print(f"binning: {t_bin:.1f}s, per-iter: {per_iter:.3f}s, "
          f"extrapolated 500-iter: {total_500:.1f}s "
          f"(baseline row-scaled: {BASELINE_500_ITERS_S:.1f}s)",
          file=sys.stderr)
    print(json.dumps({
        "metric": "higgs_proxy_1m_500iter_train_time",
        "value": round(total_500, 2),
        "unit": "s",
        "vs_baseline": round(total_500 / BASELINE_500_ITERS_S, 3),
    }))


if __name__ == "__main__":
    main()
