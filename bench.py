"""Benchmark: HIGGS-proxy binary training throughput on one TPU chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The box has zero egress, so the real HIGGS file (10.5M x 28 dense floats)
is proxied by synthetic data with the same feature count and the reference
GPU-benchmark config (max_bin=63, num_leaves=255, lr=0.1,
docs/GPU-Performance.rst:110-127).  Steady-state per-iteration time is
measured after warmup and extrapolated to the reference's 500 iterations.

Baseline: the reference's published HIGGS CPU time is 238.505 s for 500
iters on 10.5M rows (docs/Experiments.rst:101-116), row-scaled to the rows
this run measured.  vs_baseline = ours / row-scaled baseline (< 1.0 beats
the reference CPU; the GPU learner's wall-clock is only published as a
chart, >3x CPU per docs/GPU-Tutorial.rst:162).

Robustness (round-1 failure: BENCH_r01.json rc=1 after a ~25-minute axon
backend init that ended UNAVAILABLE): the parent process never imports
jax; every tier runs in its OWN subprocess with a hard timeout, and CPU
tiers get a clean environment (PALLAS_AXON_POOL_IPS cleared so the axon
sitecustomize never registers, JAX_PLATFORMS=cpu, plus an in-child
jax.config.update).  A JSON line is always emitted.
"""

import json
import os
import subprocess
import sys
import time

N_FEATURES = 28
MAX_BIN = 63
NUM_LEAVES = 255
TOTAL_ITERS_REF = 500
BASELINE_500_ITERS_S_10M5 = 238.505  # reference CPU, 10.5M rows

# (platform, rows, warmup, measured iters, subprocess timeout seconds)
# primary tier = the REAL HIGGS row count (binned 10.5M x 28 is ~300MB,
# HBM-trivial; benching 1M flattered vs_baseline by hiding the N-scaled
# terms) with 1M as the TPU fallback tier for backend hiccups.
# CPU tiers exist ONLY so an axon outage still yields a parseable record;
# they run tiny (the XLA-onehot grower on one host core is pathological at
# scale — round 3's 100k tier produced 33 s/iter) and their JSON is
# stamped {"fallback": true} so cross-round tooling never mistakes an
# outage number for a TPU measurement.
TIERS = [
    ("tpu", 10_500_000, 2, 4, 2700),
    # second shot at the primary tier: the axon backend flaps, and one
    # mid-run UNAVAILABLE should not degrade the scoreboard to 1M rows
    ("tpu", 10_500_000, 2, 4, 2700),
    ("tpu", 1_000_000, 3, 12, 1800),
    ("cpu", 10_000, 1, 3, 600),
    ("cpu", 2_000, 1, 2, 300),
]
PROBE_TIMEOUT_S = 240.0
RESULT_TAG = "BENCH_RESULT_JSON:"


def _cpu_env():
    from lightgbm_tpu.utils import cpu_subprocess_env
    return cpu_subprocess_env()


def probe_tpu(attempts: int = 2) -> bool:
    """Check the axon TPU backend comes up, in a subprocess so a hung or
    crashing tunnel can't take the bench down with it."""
    code = ("import jax; d = jax.devices(); "
            "assert d and d[0].platform != 'cpu', d; print(len(d))")
    for i in range(attempts):
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True,
                                  timeout=PROBE_TIMEOUT_S)
            if proc.returncode == 0:
                return True
            sys.stderr.write(
                f"bench: TPU probe attempt {i + 1} failed rc="
                f"{proc.returncode}: "
                f"{proc.stderr.decode(errors='replace')[-300:]}\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"bench: TPU probe attempt {i + 1} timed out "
                f"({PROBE_TIMEOUT_S:.0f}s)\n")
    return False


def run_tier_child(platform: str, n_rows: int, warmup: int,
                   measure: int) -> None:
    """Executed inside the tier subprocess; prints a tagged JSON result."""
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from lightgbm_tpu.utils import enable_jax_compilation_cache
    enable_jax_compilation_cache(os.path.dirname(os.path.abspath(__file__)))

    import numpy as np
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.core.dataset import TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objective import create_objective

    rng = np.random.RandomState(42)
    t0 = time.time()
    X = rng.normal(size=(n_rows, N_FEATURES)).astype(np.float32)
    logit = (2.0 * X[:, 0] + X[:, 1] - X[:, 2] * X[:, 3]
             + 0.5 * np.sin(3 * X[:, 4]))
    y = (logit + rng.normal(size=n_rows) * 0.5 > 0).astype(np.float64)
    t_gen = time.time() - t0

    cfg = Config(objective="binary", num_leaves=NUM_LEAVES, max_bin=MAX_BIN,
                 learning_rate=0.1, min_sum_hessian_in_leaf=100.0,
                 verbosity=-1,
                 tpu_tree_impl=os.environ.get("LIGHTGBM_TPU_IMPL", "auto"),
                 tpu_boost_chunk=int(os.environ.get(
                     "LIGHTGBM_TPU_BOOST_CHUNK", "0")))
    t0 = time.time()
    ds = TpuDataset.from_numpy(X, y, config=cfg)
    t_bin = time.time() - t0

    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    t0 = time.time()
    booster = GBDT(cfg, ds, obj)
    t_setup = time.time() - t0

    # chunked dispatch (tpu_boost_chunk, LIGHTGBM_TPU_BOOST_CHUNK): run
    # several iterations per device program with one batched fetch at the
    # chunk boundary; chunk=1 is the classic per-iteration pipeline
    chunk = booster.boost_chunk_size()

    def run_iters(n: int) -> None:
        done = 0
        while done < n:
            step = min(chunk, n - done)
            if step > 1:
                booster.train_chunk(step)
            else:
                booster.train_one_iter()
            done += step

    t0 = time.time()
    run_iters(warmup)
    jax.block_until_ready(booster.train_score)
    t_warm = time.time() - t0

    from lightgbm_tpu.utils.phase import GLOBAL_TIMER, profile_session
    from lightgbm_tpu.utils.telemetry import TELEMETRY
    GLOBAL_TIMER.reset()   # phase summary covers only the measured window
    TELEMETRY.reset()      # counters/timeline cover only the measured window
    with profile_session(), TELEMETRY.memory_session():
        t0 = time.time()
        run_iters(measure)
        jax.block_until_ready(booster.train_score)
        per_iter = (time.time() - t0) / measure

    backend = jax.default_backend()
    # report the grower that ACTUALLY ran (a requested frontier/segment
    # impl can fall back to the fused grower off-TPU or on unsupported
    # shapes — an A/B log must not attribute fused numbers to it)
    if getattr(booster, "_use_segment", False):
        impl = ("frontier" if cfg.tpu_tree_impl == "frontier"
                else "segment")
    else:
        impl = f"fused-{booster.grower_params.hist_backend}"
        if cfg.tpu_tree_impl not in ("auto", "fused"):
            impl += f" (requested {cfg.tpu_tree_impl})"
    # quality readout so impl A/B runs (LIGHTGBM_TPU_IMPL) compare
    # accuracy, not just speed: tie-corrected (midrank) train AUC from
    # the live score buffer
    score = np.asarray(booster.train_score[0], dtype=np.float64)[:n_rows]
    order = np.argsort(score, kind="stable")
    ranks = np.empty(n_rows)
    ranks[order] = np.arange(1, n_rows + 1)
    # midranks for tied scores (few distinct leaf values early on)
    uniq, inv, cnt = np.unique(score, return_inverse=True,
                               return_counts=True)
    rank_sum = np.zeros(len(uniq))
    np.add.at(rank_sum, inv, ranks)
    ranks = (rank_sum / cnt)[inv]
    n_pos = float(y.sum())
    n_neg = n_rows - n_pos
    auc = ((ranks[y > 0.5].sum() - n_pos * (n_pos + 1) / 2)
           / max(n_pos * n_neg, 1.0))
    # honest full-run accounting (round-2 verdict): a real 500-iter run
    # pays binning + setup + compile once on top of the steady state
    total_real = (t_bin + t_setup + t_warm
                  + per_iter * (TOTAL_ITERS_REF - warmup))
    sys.stderr.write(
        f"bench phases [{backend}/{impl}, {n_rows} rows]: gen={t_gen:.1f}s "
        f"bin={t_bin:.1f}s setup={t_setup:.1f}s "
        f"warmup({warmup})={t_warm:.1f}s per_iter={per_iter:.4f}s "
        f"full_500_iter_incl_overheads={total_real:.1f}s "
        f"train_auc@{warmup + measure}it={auc:.4f}\n")
    sys.stderr.write("bench " + GLOBAL_TIMER.summary() + "\n")
    # what the grower ACTUALLY decided at build time (the env gate and
    # vmem-fit veto make the bare self-check result misleading)
    from lightgbm_tpu.ops.pallas_histogram import fused_route_decisions
    fused_used = fused_route_decisions.get(
        "frontier" if impl == "frontier" else "segment")
    print(RESULT_TAG + json.dumps(
        {"per_iter": per_iter, "rows": n_rows, "backend": backend,
         "impl": impl, "auc": round(auc, 5), "chunk": chunk,
         # full-run accounting for the north-star math: a real 500-iter
         # run pays these once (t_warm is COLD here; a warm-cache rerun
         # of the same child shows the persistent-cache number)
         "bin_s": round(t_bin, 1), "warmup_s": round(t_warm, 1),
         "full_500_incl_overheads_s": round(total_real, 1),
         "fused_route": fused_used,
         # structured telemetry for the measured window (phases, fetch
         # bytes, compile seconds, network counters) — cross-round
         # tooling reads THIS, not the stderr phase line
         "metrics": TELEMETRY.metrics_blob()}))


def run_tier(platform: str, rows: int, warmup: int, measure: int,
             timeout_s: float, impl_env: str | None = None,
             chunk_env: str | None = None):
    env = _cpu_env() if platform == "cpu" else dict(os.environ)
    if impl_env is not None:
        env["LIGHTGBM_TPU_IMPL"] = impl_env
    if chunk_env is not None:
        env["LIGHTGBM_TPU_BOOST_CHUNK"] = chunk_env
    cmd = [sys.executable, os.path.abspath(__file__), "--child", platform,
           str(rows), str(warmup), str(measure)]
    proc = subprocess.run(cmd, env=env, timeout=timeout_s,
                          capture_output=True,
                          cwd=os.path.dirname(os.path.abspath(__file__)))
    sys.stderr.write(proc.stderr.decode(errors="replace"))
    if proc.returncode != 0:
        raise RuntimeError(f"tier child rc={proc.returncode}: "
                           f"{proc.stderr.decode(errors='replace')[-400:]}")
    for line in proc.stdout.decode(errors='replace').splitlines():
        if line.startswith(RESULT_TAG):
            return json.loads(line[len(RESULT_TAG):])
    raise RuntimeError("tier child produced no result line")


def maybe_ab_frontier(r, platform, rows, warmup, measure, timeout_s):
    """After a successful TPU tier, also measure tpu_tree_impl=frontier
    (the batched-MXU grower) and keep the faster result if its training
    quality matches — both are real shipped configurations, and the
    scoreboard should reflect the framework's best honest number.
    Skipped when the caller pinned an impl via LIGHTGBM_TPU_IMPL."""
    # gate on the MEASURED backend too: a tpu tier whose child silently
    # fell back to CPU must not spawn a second meaningless CPU run
    if (platform != "tpu" or r.get("backend") != "tpu"
            or os.environ.get("LIGHTGBM_TPU_IMPL")):
        return r
    if r.get("impl") == "frontier":        # auto already resolved to it
        return r
    try:
        r2 = run_tier(platform, rows, warmup, measure, timeout_s,
                      impl_env="frontier")
    except Exception as e:  # noqa: BLE001 — A/B must not kill the bench
        sys.stderr.write(f"bench: frontier A/B failed: "
                         f"{type(e).__name__}: {str(e)[-300:]}\n")
        return r
    sys.stderr.write(
        f"bench A/B: {r['impl']} per_iter={r['per_iter']:.4f} "
        f"auc={r.get('auc')} vs frontier per_iter={r2['per_iter']:.4f} "
        f"auc={r2.get('auc')}\n")
    quality_ok = (r2.get("auc") is None or r.get("auc") is None
                  or r2["auc"] >= r["auc"] - 0.002)
    if quality_ok and r2["per_iter"] < r["per_iter"]:
        return r2
    return r


def maybe_ab_chunked(r, platform, rows, warmup, measure, timeout_s):
    """After a successful tier, also measure the chunked boosting loop
    (tpu_boost_chunk: several iterations per device program, tree fetches
    batched at the chunk boundary) and keep the faster result at equal
    training quality.  The chunked and unchunked paths grow bit-identical
    trees (same PRNG stream, same fused step), so the auc gate is a
    safety net, not a tradeoff.  Skipped when the caller pinned a chunk
    size via LIGHTGBM_TPU_BOOST_CHUNK or the tier already ran chunked."""
    if os.environ.get("LIGHTGBM_TPU_BOOST_CHUNK") or r.get("chunk", 1) > 1:
        return r
    # whole number of chunks inside the measured window keeps per_iter
    # comparable; the winning impl from the frontier A/B is pinned so
    # both sides of THIS comparison run the same grower
    chunk = max(2, min(8, measure))
    impl_pin = os.environ.get("LIGHTGBM_TPU_IMPL")
    if impl_pin is None and r.get("impl") in ("frontier", "segment"):
        impl_pin = r["impl"]
    try:
        r2 = run_tier(platform, rows, warmup, measure, timeout_s,
                      impl_env=impl_pin, chunk_env=str(chunk))
    except Exception as e:  # noqa: BLE001 — A/B must not kill the bench
        sys.stderr.write(f"bench: chunked A/B failed: "
                         f"{type(e).__name__}: {str(e)[-300:]}\n")
        return r
    sys.stderr.write(
        f"bench A/B: chunk=1 per_iter={r['per_iter']:.4f} "
        f"auc={r.get('auc')} vs chunk={r2.get('chunk')} "
        f"per_iter={r2['per_iter']:.4f} auc={r2.get('auc')}\n")
    quality_ok = (r2.get("auc") is None or r.get("auc") is None
                  or r2["auc"] >= r["auc"] - 0.002)
    if quality_ok and r2["per_iter"] < r["per_iter"]:
        return r2
    return r


def main():
    want_tpu = (not os.environ.get("BENCH_SKIP_TPU")) and probe_tpu()
    for platform, rows, warmup, measure, timeout_s in TIERS:
        if platform == "tpu" and not want_tpu:
            continue
        try:
            r = run_tier(platform, rows, warmup, measure, timeout_s)
        except Exception as e:  # noqa: BLE001 — scoreboard must not die
            sys.stderr.write(f"bench: tier ({platform}, {rows}) failed: "
                             f"{type(e).__name__}: {str(e)[-400:]}\n")
            continue
        r = maybe_ab_frontier(r, platform, rows, warmup, measure, timeout_s)
        r = maybe_ab_chunked(r, platform, rows, warmup, measure, timeout_s)
        total_500 = r["per_iter"] * TOTAL_ITERS_REF
        baseline = BASELINE_500_ITERS_S_10M5 * (r["rows"] / 10_500_000)
        sys.stderr.write(
            f"bench: extrapolated 500-iter {total_500:.1f}s vs row-scaled "
            f"baseline {baseline:.1f}s on {r['rows']} rows "
            f"({r['backend']}/{r['impl']})\n")
        if r.get("metrics"):
            # human-readable digest of the structured blob (top phases,
            # transfer bytes, compile seconds) for the round log
            try:
                sys.path.insert(0, os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "tools"))
                from trace_report import summarize
                sys.stderr.write(summarize(r["metrics"]) + "\n")
            except Exception as e:  # noqa: BLE001 — report must not kill
                sys.stderr.write(f"bench: trace_report failed: {e}\n")
        out = {
            "metric": f"higgs_proxy_{r['rows']}r_500iter_train_time_"
                      f"{r['backend']}",
            "value": round(total_500, 2),
            "unit": "s",
            "vs_baseline": round(total_500 / baseline, 3),
            "impl": r["impl"],
            "chunk": r.get("chunk", 1),
            "train_auc": r.get("auc"),
            "warmup_s": r.get("warmup_s"),
            "full_500_incl_overheads_s": r.get(
                "full_500_incl_overheads_s"),
            "fused_route": r.get("fused_route"),
            "metrics": r.get("metrics"),
        }
        if r["backend"] == "cpu":
            # outage fallback: a single-core XLA run — NOT a TPU
            # measurement, never comparable across rounds.  Keyed on the
            # MEASURED backend, not the tier label: a TPU tier whose
            # child lost the chip and silently fell back to CPU must be
            # stamped too.
            out["fallback"] = True
        print(json.dumps(out))
        return
    # absolute last resort: still emit a parseable line
    print(json.dumps({
        "metric": "higgs_proxy_bench_failed",
        "value": -1.0,
        "unit": "s",
        "vs_baseline": -1.0,
    }))
    sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        run_tier_child(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
                       int(sys.argv[5]))
    else:
        main()
