/* SWIG interface for lib_lightgbm_tpu — the reference ships
 * swig/lightgbmlib.i wrapping its c_api.h for JNI/mmlspark; this wraps
 * the lightgbm_tpu C API (include/lightgbm_tpu/c_api.h) the same way:
 * pointer/array helpers plus the raw LGBM_* entry points.  Target any
 * SWIG language (-java for the mmlspark-style consumer, -python for the
 * in-repo smoke test, tests/test_swig.py). */
%module lightgbmlib
%{
#include "lightgbm_tpu/c_api.h"
%}

%include "stdint.i"
%include "cpointer.i"
%include "carrays.i"

%pointer_functions(int, intp)
%pointer_functions(long, longp)
%pointer_functions(double, doublep)
%pointer_functions(float, floatp)
%pointer_functions(int64_t, int64_tp)
%pointer_functions(void*, voidpp)

%array_functions(double, doubleArray)
%array_functions(float, floatArray)
%array_functions(int, intArray)
%array_functions(int64_t, int64Array)

%include "lightgbm_tpu/c_api.h"
