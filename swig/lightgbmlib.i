/* SWIG interface for lib_lightgbm_tpu — the reference ships
 * swig/lightgbmlib.i wrapping its c_api.h for JNI/mmlspark; this wraps
 * the lightgbm_tpu C API (include/lightgbm_tpu/c_api.h) the same way:
 * pointer/array helpers plus the raw LGBM_* entry points.  Target any
 * SWIG language (-java for the mmlspark-style consumer, -python for the
 * in-repo smoke test, tests/test_swig.py). */
%module lightgbmlib
%{
#include "lightgbm_tpu/c_api.h"
%}

%include "stdint.i"
%include "cpointer.i"
%include "carrays.i"

%pointer_functions(int, intp)
%pointer_functions(long, longp)
%pointer_functions(double, doublep)
%pointer_functions(float, floatp)
%pointer_functions(int64_t, int64_tp)
%pointer_functions(void*, voidpp)

%array_functions(double, doubleArray)
%array_functions(float, floatArray)
%array_functions(int, intArray)
%array_functions(int64_t, int64Array)

/* String-array helpers: the name-returning entry points
 * (LGBM_BoosterGetEvalNames / GetFeatureNames / DatasetGetFeatureNames)
 * follow the caller-pre-allocates contract — the caller passes a char**
 * whose slots each point at a writable buffer.  Generated bindings (JNI
 * and the Python smoke test alike) cannot express that allocation
 * natively, so provide it here: a fixed-width buffer table plus
 * getters/setters, the same facility the reference's interface file
 * ships for its JNI consumer. */
%inline %{
#include <stdlib.h>
#include <string.h>

/* The table remembers its own n/width so per-call size arguments (a
 * mismatch-prone contract) never exist: every access is bounds-checked
 * against the stored allocation. */
typedef struct {
  int n;
  int width;
  char** arr;
} StringBuffers;

static StringBuffers* new_stringBuffers(int n, int width) {
  StringBuffers* sb;
  int i;
  if (n <= 0 || width <= 1) return NULL;
  sb = (StringBuffers*)calloc(1, sizeof(StringBuffers));
  if (sb == NULL) return NULL;
  sb->n = n;
  sb->width = width;
  sb->arr = (char**)calloc((size_t)n, sizeof(char*));
  if (sb->arr == NULL) { free(sb); return NULL; }
  for (i = 0; i < n; ++i) {
    sb->arr[i] = (char*)calloc((size_t)width, 1);
    if (sb->arr[i] == NULL) { /* unwind on partial failure */
      while (--i >= 0) free(sb->arr[i]);
      free(sb->arr);
      free(sb);
      return NULL;
    }
  }
  return sb;
}

/* the char** view the LGBM_* name getters/setters expect */
static char** stringBuffers_ptr(StringBuffers* sb) {
  return sb != NULL ? sb->arr : NULL;
}

static const char* stringBuffers_getitem(StringBuffers* sb, int i) {
  if (sb == NULL || i < 0 || i >= sb->n) return NULL;
  return sb->arr[i];
}

static void stringBuffers_setitem(StringBuffers* sb, int i,
                                  const char* s) {
  if (sb == NULL || i < 0 || i >= sb->n || s == NULL) return;
  strncpy(sb->arr[i], s, (size_t)(sb->width - 1));
  sb->arr[i][sb->width - 1] = '\0';
}

static void delete_stringBuffers(StringBuffers* sb) {
  int i;
  if (sb == NULL) return;
  for (i = 0; i < sb->n; ++i) free(sb->arr[i]);
  free(sb->arr);
  free(sb);
}
%}

%include "lightgbm_tpu/c_api.h"
