/* SWIG interface for lib_lightgbm_tpu — the reference ships
 * swig/lightgbmlib.i wrapping its c_api.h for JNI/mmlspark; this wraps
 * the lightgbm_tpu C API (include/lightgbm_tpu/c_api.h) the same way:
 * pointer/array helpers plus the raw LGBM_* entry points.  Target any
 * SWIG language (-java for the mmlspark-style consumer, -python for the
 * in-repo smoke test, tests/test_swig.py). */
%module lightgbmlib
%{
#include "lightgbm_tpu/c_api.h"
%}

%include "stdint.i"
%include "cpointer.i"
%include "carrays.i"

%pointer_functions(int, intp)
%pointer_functions(long, longp)
%pointer_functions(double, doublep)
%pointer_functions(float, floatp)
%pointer_functions(int64_t, int64_tp)
%pointer_functions(void*, voidpp)

%array_functions(double, doubleArray)
%array_functions(float, floatArray)
%array_functions(int, intArray)
%array_functions(int64_t, int64Array)

/* String-array helpers: the name-returning entry points
 * (LGBM_BoosterGetEvalNames / GetFeatureNames / DatasetGetFeatureNames)
 * follow the caller-pre-allocates contract — the caller passes a char**
 * whose slots each point at a writable buffer.  Generated bindings (JNI
 * and the Python smoke test alike) cannot express that allocation
 * natively, so provide it here: a fixed-width buffer table plus
 * getters/setters, the same facility the reference's interface file
 * ships for its JNI consumer. */
/* must precede the wrapped declaration: hands the malloc'd model
 * string's ownership to the target language */
%newobject LGBM_BoosterSaveModelToStringSWIG;

%inline %{
#include <stdlib.h>
#include <string.h>

/* The table remembers its own n/width so per-call size arguments (a
 * mismatch-prone contract) never exist: every access is bounds-checked
 * against the stored allocation. */
typedef struct {
  int n;
  int width;
  char** arr;
} StringBuffers;

static StringBuffers* new_stringBuffers(int n, int width) {
  StringBuffers* sb;
  int i;
  if (n <= 0 || width <= 1) return NULL;
  sb = (StringBuffers*)calloc(1, sizeof(StringBuffers));
  if (sb == NULL) return NULL;
  sb->n = n;
  sb->width = width;
  sb->arr = (char**)calloc((size_t)n, sizeof(char*));
  if (sb->arr == NULL) { free(sb); return NULL; }
  for (i = 0; i < n; ++i) {
    sb->arr[i] = (char*)calloc((size_t)width, 1);
    if (sb->arr[i] == NULL) { /* unwind on partial failure */
      while (--i >= 0) free(sb->arr[i]);
      free(sb->arr);
      free(sb);
      return NULL;
    }
  }
  return sb;
}

/* the char** view the LGBM_* name getters/setters expect */
static char** stringBuffers_ptr(StringBuffers* sb) {
  return sb != NULL ? sb->arr : NULL;
}

static const char* stringBuffers_getitem(StringBuffers* sb, int i) {
  if (sb == NULL || i < 0 || i >= sb->n) return NULL;
  return sb->arr[i];
}

static void stringBuffers_setitem(StringBuffers* sb, int i,
                                  const char* s) {
  if (sb == NULL || i < 0 || i >= sb->n || s == NULL) return;
  strncpy(sb->arr[i], s, (size_t)(sb->width - 1));
  sb->arr[i][sb->width - 1] = '\0';
}

static void delete_stringBuffers(StringBuffers* sb) {
  int i;
  if (sb == NULL) return;
  for (i = 0; i < sb->n; ++i) free(sb->arr[i]);
  free(sb->arr);
  free(sb);
}

/* ---- typed helper battery (the reference interface ships the same
 * facilities for its JNI/mmlspark consumer, swig/lightgbmlib.i:35-200;
 * these are language-neutral — no JNIEnv — so every SWIG target gets
 * them) ---- */

/* Model-to-string with grow-on-short-buffer (the reference's
 * LGBM_BoosterSaveModelToStringSWIG).  The %newobject directive above
 * the %inline block hands buffer ownership to the target language, so
 * there is no manual free to mismatch. */
static char* LGBM_BoosterSaveModelToStringSWIG(void* handle,
                                               int start_iteration,
                                               int num_iteration,
                                               int64_t buffer_len) {
  int64_t out_len = 0;
  char* dst = (char*)malloc((size_t)(buffer_len > 1 ? buffer_len : 1));
  int result;
  if (dst == NULL) return NULL;
  result = LGBM_BoosterSaveModelToString(handle, start_iteration,
                                         num_iteration, buffer_len,
                                         &out_len, dst);
  if (result == 0 && out_len > buffer_len) {
    free(dst);
    dst = (char*)malloc((size_t)out_len);
    if (dst == NULL) return NULL;
    result = LGBM_BoosterSaveModelToString(handle, start_iteration,
                                           num_iteration, out_len,
                                           &out_len, dst);
  }
  if (result != 0) { free(dst); return NULL; }
  return dst;
}

/* Eval names with internal allocation (the reference's
 * LGBM_BoosterGetEvalNamesSWIG, minus its trust in the caller's count:
 * the C API strcpy's every ACTUAL name, so the table is sized from
 * LGBM_BoosterGetEvalCounts here — a stale caller count cannot
 * overflow).  Items are read with stringBuffers_getitem and freed with
 * delete_stringBuffers; the unused parameter keeps the reference's
 * call shape. */
static StringBuffers* LGBM_BoosterGetEvalNamesSWIG(void* handle,
                                                   int eval_counts) {
  StringBuffers* sb;
  int count = 0;
  int got = 0;
  (void)eval_counts;
  if (LGBM_BoosterGetEvalCounts(handle, &count) != 0) return NULL;
  /* width 256 bounds metric names with headroom: they come from the
   * fixed metric factory registry (metric/__init__.py), whose longest
   * name plus @k suffix is far below it — the C API's strcpy has no
   * length argument, so the registry bound is the real invariant */
  sb = new_stringBuffers(count > 0 ? count : 1, 256);
  if (sb == NULL) return NULL;
  if (LGBM_BoosterGetEvalNames(handle, &got, sb->arr) != 0
      || got > sb->n) {
    delete_stringBuffers(sb);
    return NULL;
  }
  return sb;
}

/* Dense single-row predict over a pre-filled doubleArray (the
 * reference's LGBM_BoosterPredictForMatSingle minus the JNI pinning —
 * array helpers own the buffer on every SWIG target). */
static int LGBM_BoosterPredictForMatSingleSWIG(void* handle,
                                               double* row, int ncol,
                                               int predict_type,
                                               int num_iteration,
                                               const char* parameter,
                                               int64_t* out_len,
                                               double* out_result) {
  return LGBM_BoosterPredictForMatSingleRow(
      handle, row, C_API_DTYPE_FLOAT64, ncol, 1, predict_type,
      num_iteration, parameter, out_len, out_result);
}

/* Sparse single-row predict from (indices, values) pairs: builds the
 * 2-entry CSR indptr the way the reference's
 * LGBM_BoosterPredictForCSRSingle does. */
static int LGBM_BoosterPredictForCSRSingleSWIG(void* handle,
                                               int* indices,
                                               double* values,
                                               int num_nonzeros,
                                               int64_t num_col,
                                               int predict_type,
                                               int num_iteration,
                                               const char* parameter,
                                               int64_t* out_len,
                                               double* out_result) {
  int32_t ind[2];
  ind[0] = 0;
  ind[1] = num_nonzeros;
  return LGBM_BoosterPredictForCSRSingleRow(
      handle, ind, C_API_DTYPE_INT32, (const int32_t*)indices, values,
      C_API_DTYPE_FLOAT64, 2, num_nonzeros, num_col, predict_type,
      num_iteration, parameter, out_len, out_result);
}
%}

%include "lightgbm_tpu/c_api.h"
